"""MPMD plane (ray_lightning_tpu/mpmd/): per-stage programs over DCN.

The load-bearing assertions mirror the SPMD pipeline's discipline —
scheduling is an optimization, never semantics: a 2-stage MPMD run
must land on the same final params as the SPMD pipeline AND plain ddp
(documented 2e-2 bar), while each stage verifiably compiles ONLY its
own layers (program-argument and HLO-size checks — a chunk's program
cannot compute layers whose params it never receives).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from ray_lightning_tpu.mpmd import MpmdConfig, MpmdPipelineStrategy
from ray_lightning_tpu.mpmd import channel as chan
from ray_lightning_tpu.mpmd import partition as part
from ray_lightning_tpu.mpmd import schedule as sched

TOL = 2e-2   # the repo-wide documented parity bar (README)


# -- schedules --------------------------------------------------------------


@pytest.mark.parametrize("kind", ["gpipe", "1f1b"])
@pytest.mark.parametrize("stages,micro,virtual",
                         [(2, 4, 1), (4, 8, 1), (3, 6, 1), (2, 8, 2)])
def test_schedule_invariants(kind, stages, micro, virtual):
    s = sched.build_schedule(kind, stages, micro, virtual)
    sched.validate(s)   # F-before-B, dep order, 1f1b depth bound
    assert len(s.ranks) == stages
    assert sum(len(ops) for ops in s.ranks) == 2 * stages * virtual * micro


def test_plain_1f1b_bubble_ties_gpipe():
    """The analytic fact the schedule module documents: at one chunk
    per rank, 1F1B's fill/drain bubble EQUALS GPipe's — what v=1 1F1B
    buys is the bounded stash, not the bubble."""
    g = sched.build_schedule("gpipe", 2, 4, 1)
    f = sched.build_schedule("1f1b", 2, 4, 1)
    assert f.bubble_fraction == pytest.approx(g.bubble_fraction)
    assert f.makespan == pytest.approx(g.makespan)


def test_interleaved_1f1b_beats_gpipe_bubble():
    """The bubble win comes from interleaving: >= 4 microbatches with
    v=2 chunks per rank must sit strictly below GPipe (the acceptance
    comparison bench_pipeline.py emits)."""
    for stages, micro in ((2, 4), (2, 8), (4, 8)):
        g = sched.build_schedule("gpipe", stages, micro, 1)
        f = sched.build_schedule("1f1b", stages, micro, 2)
        assert f.bubble_fraction < g.bubble_fraction, (stages, micro)


def test_1f1b_stash_depth_bounded():
    """GPipe legitimately stashes all M in-flight; 1F1B must never
    exceed stages x virtual (the memory property it exists for)."""
    s = sched.build_schedule("1f1b", 2, 16, 1)
    for ops in s.ranks:
        depth = peak = 0
        for op in ops:
            depth += 1 if op.kind == "F" else -1
            peak = max(peak, depth)
        assert peak <= 2


def test_simulate_replays_measured_times():
    s = sched.build_schedule("gpipe", 2, 4, 1)
    fast = sched.simulate(s, {(0, "F"): 0.1, (0, "B"): 0.2,
                              (1, "F"): 0.1, (1, "B"): 0.2})
    assert fast.makespan == pytest.approx(1.5)
    assert fast.bubble_fraction == pytest.approx(s.bubble_fraction)


def test_resolve_virtual_auto():
    assert sched.resolve_virtual("1f1b", 0, 2, 4) == 2
    assert sched.resolve_virtual("1f1b", 0, 1, 4) == 1   # tiny: 1 layer
    assert sched.resolve_virtual("gpipe", 0, 2, 4) == 1
    assert sched.resolve_virtual("1f1b", 3, 2, 4) == 3   # explicit wins


# -- channel ----------------------------------------------------------------
# (mailbox out-of-order + dead-peer-timeout live in
# tests/test_cluster_peer.py with the backend routing test — the peer
# channel is cluster-plane surface; here: the codec layer on top)


@pytest.mark.parametrize("mode,tol", [("none", 0.0), ("fp8", 0.08),
                                      ("int4", 0.16)])
def test_codec_round_trip(mode, tol):
    """fp32 passthrough exact; fp8/int4 within their codec error
    bounds on a [-1, 1] payload (comm plane bounds, activation path)."""
    rng = np.random.default_rng(0)
    x = rng.uniform(-1, 1, (4, 128)).astype(np.float32)
    codec = chan.ChannelCodec(mode, block_size=64)
    out = np.asarray(chan.ChannelCodec.decode(
        codec.encode(chan.ef_slot("fwd", 0), x)), np.float32)
    assert out.shape == x.shape
    assert float(np.max(np.abs(out - x))) <= tol


@pytest.mark.parametrize("mode", ["fp8", "int4"])
def test_codec_error_feedback_residual(mode):
    """EF contract on the activation path: the residual equals the
    signal-minus-decode error and is re-injected next encode — a
    repeated constant payload's RUNNING MEAN decode converges tighter
    than any single decode (the EQuARX accumulation property)."""
    rng = np.random.default_rng(1)
    x = rng.uniform(-1, 1, (2, 128)).astype(np.float32)
    codec = chan.ChannelCodec(mode, block_size=64)
    slot = chan.ef_slot("fwd", 0)
    outs = []
    for _ in range(8):
        outs.append(np.asarray(chan.ChannelCodec.decode(
            codec.encode(slot, x)), np.float32))
    single = float(np.max(np.abs(outs[0] - x)))
    mean_err = float(np.max(np.abs(np.mean(outs, axis=0) - x)))
    assert mean_err < 0.5 * single or mean_err < 1e-3
    # residual is real state and round-trips (the engine carries it in
    # the stage's optimizer state)
    state = codec.state_dict()
    assert state, "EF residual missing"
    codec2 = chan.ChannelCodec(mode, block_size=64)
    codec2.load_state_dict(state)
    np.testing.assert_array_equal(
        codec2.residuals[slot], codec.residuals[slot])


def test_codec_block_divisibility_raises():
    codec = chan.ChannelCodec("fp8", block_size=64)
    with pytest.raises(ValueError, match="block"):
        codec.encode(chan.ef_slot("fwd", 0),
                     np.zeros((2, 100), np.float32))


# -- partition --------------------------------------------------------------


def test_resolve_cuts_even_split_is_planner_choice():
    assert part.resolve_cuts(8, 2, None) == (4,)
    assert part.resolve_cuts(8, 4, None) == (2, 4, 6)


def test_resolve_cuts_validates():
    with pytest.raises(ValueError, match="cuts"):
        part.resolve_cuts(4, 2, (0,))
    with pytest.raises(ValueError, match="cuts"):
        part.resolve_cuts(4, 3, (2,))
    with pytest.raises(ValueError, match="stages"):
        part.enumerate_stage_cuts(2, 3)


def test_score_cuts_prefers_balance_and_fewer_codec_bytes():
    """Uniform layers: the balance term picks the even split; the DCN
    term is codec-aware (int4 wire < fp32 wire for the same cut)."""
    kw = dict(layer_bytes=1000, boundary_bytes=4096, n_micro=4)
    even = part.score_cuts((2,), 4, **kw)
    skew = part.score_cuts((1,), 4, **kw)
    assert even < skew
    fp32 = part.score_cuts((2,), 4, **kw)
    int4 = part.score_cuts((2,), 4, codec="int4", **kw)
    assert int4[0] < fp32[0]


def test_chunk_params_split_merge_round_trip(seed):
    from ray_lightning_tpu.models.pipeline_gpt import PipelinedGPT

    module = PipelinedGPT("tiny", dataset_size=8, batch_size=4)
    spec = module.configure_mpmd()
    x = np.zeros((4, 16), np.int32)
    full = module.init_params(jax.random.PRNGKey(0), (x, x))["params"]
    p = part.build_partition(spec, (1,))
    chunks = [p.chunk_params(full, c) for c in range(2)]
    # the head mirror of the tied wte exists on the last chunk
    assert "wte" in chunks[1] and "ln_f" in chunks[1]
    assert "wpe" in chunks[0] and "ln_f" not in chunks[0]
    merged = p.merge_params(chunks)
    for a, b in zip(jax.tree_util.tree_leaves(full),
                    jax.tree_util.tree_leaves(merged)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_interleaved_partition_requires_even_layout():
    from ray_lightning_tpu.models.pipeline_gpt import PipelinedGPT
    spec = PipelinedGPT("tiny", dataset_size=8,
                        batch_size=4).configure_mpmd()   # 2 layers
    with pytest.raises(ValueError, match="interleaved"):
        part.build_partition(spec, (1,), virtual=2)   # 2 layers / 4 chunks


# -- config / strategy wiring ----------------------------------------------


def test_config_env_round_trip(monkeypatch):
    src = MpmdConfig(stages=2, cuts=(1,), schedule="gpipe",
                     microbatches=8, codec="int4", block_size=32,
                     error_feedback=False, timeout_s=9.0)
    for k, v in src.worker_env().items():
        monkeypatch.setenv(k, v)
    assert MpmdConfig.resolve(None) == src


def test_strategy_string_resolution(monkeypatch):
    from ray_lightning_tpu.parallel.strategy import (resolve_strategy,
                                                     strategy_names)
    monkeypatch.setenv("RLT_MPMD_STAGES", "3")
    monkeypatch.setenv("RLT_MPMD_CUTS", "1,3")
    strat = resolve_strategy("mpmd")
    assert isinstance(strat, MpmdPipelineStrategy)
    assert strat.config.stages == 3 and strat.config.cuts == (1, 3)
    assert "mpmd" in strategy_names()
    # the declared activation exchange rides the _dcn suffix so the
    # planner/metrics planes score it at the DCN link
    assert "activation_exchange_dcn" in strat.step_collective_bytes(
        None, None)


def test_unsupported_trainer_knobs_raise(seed):
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.models.pipeline_gpt import PipelinedGPT

    module = PipelinedGPT("tiny", dataset_size=16, batch_size=8)
    trainer = Trainer(max_steps=1, strategy="mpmd",
                      gradient_clip_val=1.0, enable_checkpointing=False,
                      num_sanity_val_steps=0, limit_val_batches=0, seed=0)
    with pytest.raises(ValueError, match="gradient_clip_val"):
        trainer.fit(module)
    trainer = Trainer(max_steps=1, strategy="mpmd",
                      enable_checkpointing=False, num_sanity_val_steps=0,
                      limit_val_batches=0, seed=0)
    with pytest.raises(ValueError, match="fit only"):
        trainer.validate(module)


# -- parity (the acceptance bar) -------------------------------------------


def _fit(strategy, max_steps=4, micro=None):
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.models.pipeline_gpt import PipelinedGPT

    module = PipelinedGPT("tiny", n_microbatches=2, dataset_size=16,
                          batch_size=8)
    trainer = Trainer(max_epochs=2, max_steps=max_steps,
                      strategy=strategy, enable_checkpointing=False,
                      num_sanity_val_steps=0, limit_val_batches=0,
                      log_every_n_steps=1, seed=0)
    trainer.fit(module)
    return trainer


def _worst_diff(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return max(float(np.max(np.abs(np.asarray(x, np.float32)
                                   - np.asarray(y, np.float32))))
               for x, y in zip(la, lb))


@pytest.fixture(scope="module")
def parity_runs():
    """One fit per flavor, shared across the parity assertions (each
    fit pays tiny-GPT compiles).  ``jax_threefry_partitionable`` makes
    rng lowering sharding-invariant for the comparison window: without
    it the SPMD pipeline's stage-sharded INIT draws different (equally
    random) kernels than a single-device init — this jax build
    defaults it off — and no schedule could reconcile two different
    initializations (measured: 0.55 max kernel diff at step 0)."""
    from ray_lightning_tpu.parallel.pipeline import PipelineStrategy

    prev = jax.config.jax_threefry_partitionable
    jax.config.update("jax_threefry_partitionable", True)
    try:
        ddp = _fit("ddp")
        spmd_pipe = _fit(PipelineStrategy(stages=2))
        mpmd = _fit(MpmdPipelineStrategy(MpmdConfig(
            stages=2, schedule="1f1b", microbatches=4)))
        yield {"ddp": ddp, "pipeline": spmd_pipe, "mpmd": mpmd}
    finally:
        jax.config.update("jax_threefry_partitionable", prev)


def test_mpmd_matches_spmd_pipeline_and_ddp(parity_runs):
    """THE acceptance bar: 2-stage MPMD tiny-GPT final params within
    the documented 2e-2 of the SPMD pipeline AND plain ddp."""
    pm = parity_runs["mpmd"].state.params
    for ref in ("pipeline", "ddp"):
        diff = _worst_diff(parity_runs[ref].state.params, pm)
        assert diff < TOL, f"mpmd vs {ref}: worst param diff {diff}"
    assert parity_runs["mpmd"].callback_metrics["loss"] == pytest.approx(
        parity_runs["ddp"].callback_metrics["loss"], rel=2e-2)


def test_each_stage_compiled_only_its_own_layers(parity_runs):
    """Per-stage-program evidence: every chunk's program arguments
    carry ONLY its layer slice (it cannot compute the others), the
    slices cover the model exactly once (+ the tied mirror), and each
    stage's compiled fwd+bwd HLO is smaller than the monolithic train
    step the SPMD pipeline compiles on every host."""
    trainer = parity_runs["mpmd"]
    report = trainer._mpmd_report
    module = trainer.lightning_module
    spec = module.configure_mpmd()

    full = module.init_params(
        jax.random.PRNGKey(0),
        (np.zeros((4, 64), np.int32),) * 2)["params"]
    n_full = sum(int(np.prod(v.shape)) for v in
                 jax.tree_util.tree_leaves(full))
    tied = sum(int(np.prod(np.asarray(full[k]).shape))
               for k in spec.tied_keys)
    per_stage = report["per_stage_param_elements"]
    assert len(per_stage) == 2
    assert all(n < n_full for n in per_stage), \
        "a stage program received the whole model"
    assert sum(per_stage) == n_full + tied   # exact cover + mirror

    # monolith: the full train step every SPMD-pipeline host compiles
    from ray_lightning_tpu.core.steps import (build_init_fn,
                                              build_train_step)
    tx = module.configure_optimizers()
    batch = jax.tree_util.tree_map(
        np.asarray, next(iter(module.train_dataloader())))
    abstract = jax.eval_shape(build_init_fn(module, tx),
                              jax.random.PRNGKey(0), batch)
    mono = jax.jit(build_train_step(module, tx)).lower(
        abstract, batch).compile()
    mono_bytes = len(mono.as_text())
    for stage_hlo in report["per_stage_hlo_bytes"]:
        assert sum(stage_hlo.values()) < mono_bytes, (
            f"stage programs {stage_hlo} not smaller than the "
            f"{mono_bytes}-byte monolith")


def test_mpmd_report_shape(parity_runs):
    report = parity_runs["mpmd"]._mpmd_report
    assert report["cuts"] == [1]
    assert report["schedule"] == "1f1b"
    assert len(report["per_stage_compile_seconds"]) == 2
    assert report["activation_bytes_per_step"] > 0
    assert set(report["bubble"]) == {"gpipe", "1f1b"}
    # EF/channel state rides the stage opt state in trainer.state
    assert set(parity_runs["mpmd"].state.opt_state) == {"chunk0",
                                                        "chunk1"}
    assert "channel_ef" in parity_runs["mpmd"].state.opt_state["chunk0"]


def test_mpmd_codec_on_activation_path_stays_close(seed):
    """fp8 codec + EF on the stage boundary: training stays within the
    documented parity bar of the codec-off run over a few steps, and
    the EF residual lands in the stage optimizer state."""
    base = _fit(MpmdPipelineStrategy(MpmdConfig(
        stages=2, schedule="gpipe", microbatches=4)))
    fp8 = _fit(MpmdPipelineStrategy(MpmdConfig(
        stages=2, schedule="gpipe", microbatches=4, codec="fp8")))
    diff = _worst_diff(base.state.params, fp8.state.params)
    assert diff < TOL, f"fp8 activation codec drift {diff}"
    ef = fp8.state.opt_state["chunk0"]["channel_ef"]
    assert ef, "error-feedback residual not carried in optimizer state"


def test_mpmd_actor_mode_matches_in_process(seed, monkeypatch):
    """The true MPMD shape: per-stage cluster actors exchanging
    activations over the worker↔worker peer channel must land on
    BIT-IDENTICAL params to the in-process engine (same programs, same
    schedule, same channel — only the transport differs)."""
    monkeypatch.setenv("RLT_BACKEND", "local")
    from ray_lightning_tpu.cluster.backend import set_backend
    set_backend(None)   # fresh backend under the env override
    try:
        t_in = _fit(MpmdPipelineStrategy(MpmdConfig(
            stages=2, schedule="gpipe", microbatches=4)), max_steps=2)
        t_act = _fit(MpmdPipelineStrategy(MpmdConfig(
            stages=2, schedule="gpipe", microbatches=4, actors=True,
            timeout_s=120)), max_steps=2)
        assert _worst_diff(t_in.state.params, t_act.state.params) == 0.0
        assert t_act._mpmd_report["mode"] == "actors"
        ranks = [s["rank"] for s in t_act._mpmd_report["setup"]]
        assert ranks == [0, 1]
    finally:
        set_backend(None)
