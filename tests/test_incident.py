"""Incident plane (telemetry/incident.py): bounded timelines, rolling
median+MAD anomaly detectors, and auto-RCA incident reports that arm
their own evidence.

The e2e case mirrors the plane's reason to exist: a 2-worker fit with
an injected bounded straggler (``RLT_FAULT=slow:...,count=N``) must
open an incident AT RUNTIME that names the slow rank with measured
(anatomy-backed) attribution, link its evidence files, and close the
incident once the fault clears — no post-hoc rerun with a profiler.
"""

import json
import os

import pytest

from ray_lightning_tpu import Trainer, telemetry
from ray_lightning_tpu.models import BoringModel
from ray_lightning_tpu.telemetry import TelemetryConfig
from ray_lightning_tpu.telemetry.aggregator import TelemetryAggregator
from ray_lightning_tpu.telemetry.incident import (
    INCIDENT_SCHEMA_KEYS,
    ArmWatcher,
    Detector,
    DetectorConfig,
    IncidentConfig,
    IncidentManager,
    TimelineStore,
    write_arm_file,
)

from tests.utils import cpu_plugin


@pytest.fixture(autouse=True)
def _clean_telemetry():
    yield
    telemetry.disable()
    telemetry.disable_anatomy()
    telemetry.disable_metrics()
    telemetry.set_active(None)


# -- timeline store ------------------------------------------------------

def test_timeline_ring_bounded_memory():
    """The memory invariant: any run length, fixed ring size."""
    tl = TimelineStore(capacity=16)
    for i in range(10_000):
        tl.note("step_wall_s", 0, float(i), ts=float(i))
    pts = tl.samples("step_wall_s", 0)
    assert len(pts) == 16
    # newest samples win (it's a ring, not a head-keep)
    assert pts[-1] == (9999.0, 9999.0)
    assert pts[0] == (9984.0, 9984.0)
    st = tl.stats()
    assert st["keys"] == 1 and st["capacity"] == 16


def test_timeline_key_cardinality_capped():
    """A label-cardinality explosion cannot grow the driver: distinct
    (series, rank) rings are capped, overflow is counted not stored."""
    tl = TimelineStore(capacity=16, max_keys=4)
    for rank in range(10):
        tl.note("ttft_p99_s", rank, 0.5)
    st = tl.stats()
    assert st["keys"] == 4
    assert st["dropped_keys"] == 6
    assert tl.window()["dropped_keys"] == 6


def test_timeline_window_filters_and_downsample():
    tl = TimelineStore(capacity=512)
    for i in range(100):
        tl.note("step_wall_s", 0, float(i), ts=1000.0 + i)
        tl.note("data_wait_s", 1, 0.01, ts=1000.0 + i)
    tl.note_event("compile", ts=1050.0, rank=0, seconds=1.5)
    doc = tl.window(series="step_wall_s", rank=0, downsample=10)
    assert set(doc["series"]) == {"step_wall_s"}
    pts = doc["series"]["step_wall_s"]["0"]
    assert len(pts) <= 11           # stride keep-newest may add one
    assert pts[-1] == [1099.0, 99.0]   # newest sample always kept
    assert doc["events"] and doc["events"][0]["event"] == "compile"
    # unfiltered doc carries both series
    assert set(tl.window()["series"]) == {"step_wall_s", "data_wait_s"}


# -- detectors -----------------------------------------------------------

def _fed(det, values, t):
    out = []
    for v in values:
        t[0] += 1.0
        out.append(det.observe(v, ts=t[0]))
    return out


def test_detector_no_false_trip_flat_and_noisy():
    t = [0.0]
    cfg = DetectorConfig(warmup=8, patience=2, cooldown_s=1.0)
    flat = Detector("step_wall_s", 0, cfg, clock=lambda: t[0])
    assert all(r is None for r in _fed(flat, [0.05] * 50, t))
    assert not flat.tripped
    noisy = Detector("step_wall_s", 1, cfg, clock=lambda: t[0])
    vals = [0.05 + 0.004 * ((i * 13) % 7) / 7 for i in range(50)]
    assert all(r is None for r in _fed(noisy, vals, t))
    assert not noisy.tripped and noisy.trips == 0


def test_detector_trips_on_spike_after_patience():
    t = [0.0]
    det = Detector("step_wall_s", 1,
                   DetectorConfig(warmup=8, patience=3, cooldown_s=1.0),
                   clock=lambda: t[0])
    _fed(det, [0.05] * 12, t)
    # patience 3: two breached samples are noise
    assert _fed(det, [0.5, 0.5], t) == [None, None]
    assert not det.tripped and det._streak == 2
    (ev,) = _fed(det, [0.5], t)
    assert ev["transition"] == "opened"
    assert ev["value"] == 0.5 and ev["direction"] == "high"
    assert ev["band"][0] < 0.05 < ev["band"][1] < 0.5
    assert det.tripped and det.trips == 1
    # a healthy sample mid-streak resets patience (consecutive, not
    # cumulative): pin on a fresh detector
    det2 = Detector("step_wall_s", 2,
                    DetectorConfig(warmup=8, patience=3, cooldown_s=1.0),
                    clock=lambda: t[0])
    _fed(det2, [0.05] * 12, t)
    assert _fed(det2, [0.5, 0.5, 0.05, 0.5, 0.5], t) == [None] * 5
    assert not det2.tripped


def test_detector_close_then_cooldown_state_machine():
    t = [0.0]
    cfg = DetectorConfig(warmup=8, patience=2, cooldown_s=10.0)
    det = Detector("step_wall_s", 0, cfg, clock=lambda: t[0])
    _fed(det, [0.05] * 12, t)
    opened = _fed(det, [0.5, 0.5], t)
    assert opened[-1]["transition"] == "opened"
    # while tripped, breaches keep it open and healthy samples must be
    # consecutive to close
    assert _fed(det, [0.5, 0.05, 0.5], t) == [None] * 3
    assert det.tripped
    closed = _fed(det, [0.05, 0.05], t)
    assert closed[-1]["transition"] == "closed"
    assert not det.tripped and det.in_cooldown
    # inside the cooldown window the same breach cannot re-trip
    assert _fed(det, [0.5, 0.5, 0.5], t) == [None] * 3
    assert det.trips == 1
    # past the cooldown it trips again
    t[0] += cfg.cooldown_s
    reopened = _fed(det, [0.5, 0.5], t)
    assert reopened[-1]["transition"] == "opened"
    assert det.trips == 2


def test_detector_low_direction_dips():
    t = [0.0]
    det = Detector("goodput_fraction", -1,
                   DetectorConfig(direction="low", warmup=4, patience=1),
                   clock=lambda: t[0])
    _fed(det, [0.8] * 6, t)
    assert not det.breaches(2.0)     # high is fine for a "low" detector
    (ev,) = _fed(det, [0.05], t)
    assert ev["transition"] == "opened"


# -- incident manager ----------------------------------------------------

def _manager(tmp_path, t, **cfg_kw):
    kw = dict(warmup=4, patience=2, cooldown_s=0.0)
    kw.update(cfg_kw)
    return IncidentManager(str(tmp_path), cfg=IncidentConfig(**kw),
                           run_kind="fit", clock=lambda: t[0])


def _feed_steps(mgr, t, values, rank=1, t0=100.0):
    for v in values:
        t[0] += 1.0
        mgr.note_sample("step_wall_s", rank, v, ts=t0 + t[0])


def test_manager_open_close_dump_schema(tmp_path):
    t = [0.0]
    mgr = _manager(tmp_path, t)
    _feed_steps(mgr, t, [0.05] * 10)
    assert not mgr.open_incidents
    _feed_steps(mgr, t, [0.5, 0.5])
    (inc,) = mgr.open_incidents
    assert inc.series == "step_wall_s" and inc.rank == 1
    assert inc.path and os.path.exists(inc.path)
    assert os.path.basename(inc.path) == f"incident_{inc.id}.json"
    with open(inc.path) as f:
        doc = json.load(f)
    assert set(doc) == set(INCIDENT_SCHEMA_KEYS)
    assert doc["state"] == "open" and doc["trigger"]["value"] == 0.5
    # recovery closes it and the dump is refreshed in place
    _feed_steps(mgr, t, [0.05, 0.05])
    assert not mgr.open_incidents
    with open(inc.path) as f:
        doc = json.load(f)
    assert doc["state"] == "closed"
    assert doc["closed_ts"] >= doc["opened_ts"]
    assert doc["trigger"]["cleared"]["value"] == 0.05
    # metric surface: one counter row per (series, verdict) + the gauge
    samples = mgr.metric_samples()
    by_name = {m["name"] for m in samples}
    assert by_name == {"rlt_incident_total", "rlt_incident_active"}
    active = [m for m in samples if m["name"] == "rlt_incident_active"]
    assert active[0]["value"] == 0
    total = [m for m in samples if m["name"] == "rlt_incident_total"]
    assert sum(m["value"] for m in total) == 1
    assert total[0]["labels"]["series"] == "step_wall_s"


def test_manager_goodput_delta_and_events_evidence(tmp_path):
    t = [0.0]
    mgr = _manager(tmp_path, t)
    mgr.note_goodput({"goodput_fraction": 0.8,
                      "buckets": {"step": 10.0, "data_wait": 1.0}})
    mgr.note_event("snapshot_stall", seconds=0.25)
    _feed_steps(mgr, t, [0.05] * 10 + [0.5, 0.5])
    (inc,) = mgr.open_incidents
    assert inc.evidence["goodput_open"]["goodput_fraction"] == 0.8
    assert [e["event"] for e in inc.evidence["events"]] == \
        ["snapshot_stall"]
    # the stall inside the window is a ranked cause
    assert inc.verdict == "snapshot-stall", inc.causes
    mgr.note_goodput({"goodput_fraction": 0.5,
                      "buckets": {"step": 12.0, "data_wait": 4.0}})
    _feed_steps(mgr, t, [0.05, 0.05])
    assert inc.state == "closed"
    assert inc.evidence["goodput_delta"] == {"step": 2.0,
                                             "data_wait": 3.0}


def test_manager_anatomy_attribution_names_straggler(tmp_path):
    """The armed window's measured exposed-comm shares attribute the
    incident: the rank that never waits in the collective is the one
    everyone waits FOR."""
    t = [0.0]
    mgr = _manager(tmp_path, t)
    _feed_steps(mgr, t, [0.05] * 10 + [0.5, 0.5])
    (inc,) = mgr.open_incidents
    mgr.note_anatomy(0, {"wall_s": 0.5, "exposed_s": 0.4,
                         "compute_s": 0.05, "host_s": 0.05},
                     capture_dir="/tmp/anat0")
    mgr.note_anatomy(1, {"wall_s": 0.5, "exposed_s": 0.01,
                         "compute_s": 0.05, "host_s": 0.44})
    assert inc.verdict == "straggler-rank", inc.causes
    assert inc.causes[0]["detail"]["rank"] == 1
    assert set(inc.evidence["anatomy"]) == {"0", "1"}
    assert inc.evidence["anatomy_dir"] == "/tmp/anat0"


def test_manager_divergence_and_bounded_retention(tmp_path):
    t = [0.0]
    mgr = _manager(tmp_path, t, max_incidents=3)
    inc = mgr.note_divergence({"ratio": 1.8, "modeled_comm_s": 1.0})
    assert inc is not None and inc.verdict == "replan-recommended"
    assert inc.series == "plan_divergence"
    assert mgr.note_divergence({"ratio": 1.2}) is None   # inside band
    for _ in range(6):
        mgr.note_divergence({"ratio": 3.0})
    assert len(mgr.incidents) == 3      # retention bound holds
    # export-time sweep closes whatever is still open
    mgr.close_all(reason="run_end")
    assert not mgr.open_incidents
    assert all(i.trigger["cleared"]["reason"] == "run_end"
               for i in mgr.incidents)


def test_manager_disabled_is_inert(tmp_path):
    t = [0.0]
    mgr = IncidentManager(str(tmp_path),
                          cfg=IncidentConfig(enabled=False),
                          clock=lambda: t[0])
    _feed_steps(mgr, t, [0.05] * 10 + [9.0] * 5)
    assert not mgr.incidents
    assert mgr.stats() == {"enabled": False}
    assert mgr.metric_samples() == []


def test_heartbeat_tail_deduped_by_watermark(tmp_path):
    """Tail entries the span path already fed (same step, timestamps
    within the 50ms slack) must not double-count; genuinely newer
    entries must land."""
    t = [0.0]
    mgr = _manager(tmp_path, t)
    mgr.note_sample("step_wall_s", 0, 0.05, ts=1000.0)
    mgr.note_tail(0, [
        {"s": "step_wall_s", "ts": 999.5, "v": 0.05},    # older
        {"s": "step_wall_s", "ts": 1000.04, "v": 0.05},  # within slack
        {"s": "step_wall_s", "ts": 1001.0, "v": 0.06},   # new
        {"s": "step_wall_s", "v": 0.07},                 # malformed
    ])
    pts = mgr.timeline.samples("step_wall_s", 0)
    assert [p[0] for p in pts] == [1000.0, 1001.0]
    # and the watermark advanced: replaying the same tail adds nothing
    mgr.note_tail(0, [{"s": "step_wall_s", "ts": 1001.0, "v": 0.06}])
    assert len(mgr.timeline.samples("step_wall_s", 0)) == 2


def test_arm_file_roundtrip_once_per_id(tmp_path):
    path = str(tmp_path / "incident" / "arm.json")
    t = [0.0]
    w = ArmWatcher(path, min_poll=0.25, clock=lambda: t[0])
    assert w.poll() is None                  # no file yet
    assert write_arm_file(path, "abc123", steps=4)
    t[0] += 0.3
    ctl = w.poll()
    assert ctl["id"] == "abc123" and ctl["steps"] == 4
    t[0] += 0.3
    assert w.poll() is None                  # same id: seen
    assert write_arm_file(path, "def456", steps=2)
    t[0] += 0.1
    assert w.poll() is None                  # throttled (min_poll)
    t[0] += 0.25
    assert w.poll()["id"] == "def456"


# -- aggregator integration ---------------------------------------------

def _span(name, rank, ts, dur, **attrs):
    r = {"t": "span", "name": name, "rank": rank, "ts": ts, "dur": dur,
         "depth": 0}
    if attrs:
        r["attrs"] = attrs
    return r


def test_aggregator_feeds_timeline_from_spans(tmp_path):
    agg = TelemetryAggregator(str(tmp_path), heartbeat_timeout=60)
    for i in range(5):
        agg.ingest_records(0, [
            _span("step", 0, 1000.0 + i, 0.08, k=2),
            _span("data_wait", 0, 1000.5 + i, 0.01),
        ])
    # step wall normalized per-step by the chunk size k
    walls = agg.incidents.timeline.samples("step_wall_s", 0)
    assert len(walls) == 5 and abs(walls[0][1] - 0.04) < 1e-9
    # cadence series: start-to-start deltas, normalized by the PREVIOUS
    # span's k (4 intervals from 5 steps)
    ivals = agg.incidents.timeline.samples("step_interval_s", 0)
    assert len(ivals) == 4 and abs(ivals[0][1] - 0.5) < 1e-9
    assert len(agg.incidents.timeline.samples("data_wait_s", 0)) == 5
    doc = agg.timeline_window(series="step_wall_s", rank=0)
    assert set(doc["series"]) == {"step_wall_s"}
    assert agg.incident_stats()["enabled"] is True


def test_aggregator_status_sections_memoized_per_epoch(tmp_path):
    """Satellite: /status section assembly recomputes only when the
    ingest epoch moved — idle scrapes are dict lookups."""
    agg = TelemetryAggregator(str(tmp_path), heartbeat_timeout=60)
    agg.ingest_records(0, [_span("step", 0, 1000.0, 0.05)])
    first = agg.step_stats()
    assert agg.step_stats() is first            # cached object, no work
    assert agg.memo_recomputes["step_stats"] == 1
    agg.ingest_records(0, [_span("step", 0, 1001.0, 0.05)])
    second = agg.step_stats()
    assert second["per_rank"]["0"]["steps"] == 2
    assert agg.memo_recomputes["step_stats"] == 2
    # the first liveness verdict is a real change (bumps the epoch);
    # the watchdog's re-probes of the SAME verdict must not
    agg.note_worker_alive(0, True)
    third = agg.step_stats()
    recomputes = agg.memo_recomputes["step_stats"]
    epoch_before = agg._epoch
    agg.note_worker_alive(0, True)
    agg.note_worker_alive(0, True)
    assert agg._epoch == epoch_before
    assert agg.step_stats() is third
    assert agg.memo_recomputes["step_stats"] == recomputes


def test_aggregator_serve_signals_and_export_summary(tmp_path):
    agg = TelemetryAggregator(str(tmp_path), heartbeat_timeout=60,
                              run_kind="serve")
    for _ in range(8):
        agg.note_serve_signals(queue_depth=2, ttft_p99_s=0.1,
                               tpot_p99_s=0.02)
    for s in ("queue_depth", "ttft_p99_s", "tpot_p99_s"):
        assert len(agg.incidents.timeline.samples(s, -1)) == 8, s
    # an explicit-verdict incident lands in the export summary and
    # keeps its verdict through the run-end close
    agg.incidents.note_divergence({"ratio": 2.5})
    summary = agg.export()["summary"]
    assert summary["incidents"]["total"] == 1
    assert "plan_divergence/replan-recommended" in \
        summary["incidents"]["by_verdict"]
    assert not agg.incidents.open_incidents    # export closes the run


# -- config resolution ---------------------------------------------------

def test_resolved_incident_env_precedence(monkeypatch):
    from ray_lightning_tpu.telemetry import incident as inc_mod

    for k in (inc_mod.INCIDENT_ENV, inc_mod.INCIDENT_WARMUP_ENV,
              inc_mod.INCIDENT_PATIENCE_ENV):
        monkeypatch.delenv(k, raising=False)
    cfg = TelemetryConfig(incident_warmup=5, incident_patience=4)
    r = cfg.resolved_incident()
    assert r.enabled and r.warmup == 5 and r.patience == 4
    # env outranks config fields (the worker/operator override channel)
    monkeypatch.setenv(inc_mod.INCIDENT_WARMUP_ENV, "9")
    assert cfg.resolved_incident().warmup == 9
    monkeypatch.setenv(inc_mod.INCIDENT_WARMUP_ENV, "bogus")
    assert cfg.resolved_incident().warmup == 5     # malformed: ignored
    monkeypatch.setenv(inc_mod.INCIDENT_ENV, "0")
    assert not cfg.resolved_incident().enabled
    monkeypatch.delenv(inc_mod.INCIDENT_ENV)
    # worker_env ships the disarm (and only the disarm: the default-on
    # case adds nothing, pinned by telemetry/selfcheck.py)
    assert inc_mod.INCIDENT_ENV not in TelemetryConfig().worker_env()
    env = TelemetryConfig(incident=False).worker_env()
    assert env[inc_mod.INCIDENT_ENV] == "0"


def test_fault_slow_count_bounds_straggler():
    from ray_lightning_tpu.elastic.faults import parse_fault

    spec = parse_fault("slow:rank=1,step=5,seconds=0.01,count=3")
    fired = [s for s in range(1, 12) if spec.should_fire(1, s)]
    assert fired == [5, 6, 7]            # bounded: [step, step+count)
    assert not spec.should_fire(0, 6)    # wrong rank
    assert spec.describe() == "slow:rank=1,step=5,seconds=0.01,count=3"
    # count=1 default keeps the legacy unbounded straggler
    legacy = parse_fault("slow:rank=1,step=5,seconds=0.01")
    assert legacy.should_fire(1, 500)


# -- end-to-end over the cluster backend --------------------------------

@pytest.mark.slow
def test_e2e_slow_rank_opens_and_closes_incident(tmp_path, seed):
    """2-worker fit with a bounded straggler on rank 1: the driver must
    open an incident at runtime, arm an anatomy window whose measured
    exposed-comm shares NAME rank 1, link the evidence files, and close
    the incident after the fault clears."""
    trainer = Trainer(
        max_epochs=1, limit_train_batches=40, limit_val_batches=0,
        num_sanity_val_steps=0, enable_checkpointing=False, seed=0,
        log_every_n_steps=10**9,
        plugins=[cpu_plugin(2, worker_env={
            "RLT_FAULT": "slow:rank=1,step=16,seconds=0.35,count=12"})],
        default_root_dir=str(tmp_path),
        telemetry={"heartbeat_interval": 0.2,
                   # cadence effectively off: the only way a window
                   # can happen is the incident arming it
                   "anatomy_every_n_steps": 10_000,
                   "anatomy_steps": 2,
                   "incident_warmup": 8,
                   "incident_patience": 2,
                   "incident_cooldown_s": 0.5})
    # 192 rows / batch 2 / 2 ranks = 48 per-rank batches >= the 40 limit
    trainer.fit(BoringModel(dataset_length=192))

    agg = trainer.plugin._telemetry_agg
    incidents = agg.incidents.incidents
    assert incidents, "no incident opened for an injected straggler"
    # the straggler's own sleep lands BETWEEN its step spans, so the
    # cadence/wall detectors trip on rank 1 (and possibly on rank 0,
    # whose collective waits for it) — at least one incident must name
    # rank 1 on a step-time series
    rank1 = [i for i in incidents
             if i.rank == 1 and i.series in ("step_interval_s",
                                             "step_wall_s",
                                             "data_wait_s")]
    assert rank1, [(i.series, i.rank) for i in incidents]
    inc = rank1[0]
    # the fault is bounded (count=12 of 40 steps): the incident closed
    assert inc.state == "closed", inc.brief()
    # evidence armed at open: flight ring dump + the arm file
    assert inc.evidence.get("anatomy_armed") is True
    flight = inc.evidence.get("flight_dumps", {}).get("1")
    assert flight and os.path.exists(flight)
    # the armed anatomy window landed DURING the fault and the measured
    # exposed-comm shares attribute the incident to rank 1 (lowest
    # share: its peers wait in the collective, it never does)
    attributed = [i for i in incidents
                  if i.verdict == "straggler-rank"]
    assert attributed, [(i.series, i.rank, i.verdict, i.causes)
                        for i in incidents]
    assert attributed[0].causes[0]["detail"]["rank"] == 1
    anatomy_ev = attributed[0].evidence["anatomy"]
    assert set(anatomy_ev) >= {"0", "1"}
    # the report is on disk with the pinned schema
    with open(inc.path) as f:
        doc = json.load(f)
    assert set(doc) == set(INCIDENT_SCHEMA_KEYS)
    # surfaced in the export summary (same doc /status serves)
    summary = trainer._telemetry_paths["summary"]
    assert summary["incidents"]["total"] >= 1
    assert summary["incidents"]["by_verdict"], summary["incidents"]
