"""Feature-composition matrix: trainer options × sharding strategies.

Individually-tested features (gradient accumulation, precision, grad
clipping) must keep working when combined with non-default strategies —
the combinations users actually run.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu import Trainer
from ray_lightning_tpu.core.module import LightningModule
from ray_lightning_tpu.models import BoringModel
from ray_lightning_tpu.models.gpt import (GPTLightningModule,
                                          gpt_partition_rules)
from ray_lightning_tpu.parallel.strategy import SpmdStrategy


def _fit(strategy=None, **kw):
    module = kw.pop("module", None) or BoringModel(batch_size=8)
    trainer = Trainer(max_epochs=1, limit_train_batches=4,
                      limit_val_batches=0, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      log_every_n_steps=1, strategy=strategy, **kw)
    trainer.fit(module)
    assert np.isfinite(float(trainer.callback_metrics["loss"]))
    return trainer


@pytest.mark.parametrize("strategy", ["ddp", "zero1", "fsdp"])
def test_chunked_dispatch_with_strategies(strategy, seed):
    """steps_per_execution composes with every sharding strategy (the
    multi-step scan carries the sharded TrainState through its body)."""
    t = _fit(strategy=strategy, steps_per_execution=2,
             module=BoringModel(batch_size=8, dataset_length=64))
    assert t.global_step == 4


@pytest.mark.parametrize("strategy", ["ddp", "zero1", "fsdp"])
def test_dataset_cache_with_strategies(strategy, seed):
    """cache_train_dataset composes with sharded state: the on-device
    gather feeds a batch into the same sharded step."""
    t = _fit(strategy=strategy, steps_per_execution=2,
             cache_train_dataset=True,
             module=BoringModel(batch_size=8, dataset_length=64))
    assert t.global_step == 4


def test_chunked_dispatch_with_accumulation(seed):
    """steps_per_execution (outer scan) and accumulate_grad_batches
    (inner scan) nest: 4 loader batches = 2 chunks x (2 micro-steps)."""
    t = _fit(steps_per_execution=2, accumulate_grad_batches=2,
             module=BoringModel(batch_size=8, dataset_length=64))
    assert t.global_step == 4


def test_cache_with_bf16_precision(seed):
    """The cached dataset is stored in the cast dtype, so bf16 input
    precision composes with on-device gathering."""
    t = _fit(precision="bf16", steps_per_execution=2,
             cache_train_dataset=True,
             module=BoringModel(batch_size=8, dataset_length=64))
    assert t.global_step == 4


@pytest.mark.parametrize("strategy", ["ddp", "zero1", "fsdp"])
def test_grad_accumulation_with_strategies(strategy, seed):
    t = _fit(strategy=strategy, accumulate_grad_batches=2)
    assert t.global_step == 4


def test_grad_accumulation_with_spmd_mesh(seed):
    module = GPTLightningModule("tiny", dataset_size=32, batch_size=8)
    strategy = SpmdStrategy(rules=gpt_partition_rules(),
                            axis_names=("data", "tensor"),
                            axis_sizes={"tensor": 2})
    t = _fit(strategy=strategy, module=module, accumulate_grad_batches=2)
    assert t.global_step > 0


def test_accumulation_matches_large_batch(seed):
    """k microbatches of size b must produce the same first-step update
    as one batch of size k*b (gradient averaging correctness) — checked
    through the full Trainer path with a deterministic SGD module."""
    import optax

    class Linear(LightningModule):
        def __init__(self, batch_size):
            super().__init__()
            self.batch_size = batch_size

        def configure_model(self):
            import flax.linen as nn
            return nn.Dense(2)

        def configure_optimizers(self):
            return optax.sgd(0.1)

        def training_step(self, ctx, batch):
            x, y = batch
            loss = ((ctx.apply(x) - y) ** 2).mean()
            ctx.log("loss", loss)
            return loss

        def train_dataloader(self):
            from ray_lightning_tpu.core.data import ArrayDataset, DataLoader
            rng = np.random.default_rng(0)
            x = rng.normal(size=(16, 4)).astype(np.float32)
            y = rng.normal(size=(16, 2)).astype(np.float32)
            return DataLoader(ArrayDataset(x, y),
                              batch_size=self.batch_size, drop_last=True)

    def one_step(batch_size, accum):
        m = Linear(batch_size)
        t = Trainer(max_steps=1, max_epochs=1, enable_checkpointing=False,
                    num_sanity_val_steps=0, limit_val_batches=0, seed=0,
                    accumulate_grad_batches=accum, log_every_n_steps=1)
        t.fit(m)
        return jax.tree_util.tree_map(np.asarray, t.state.params)

    p_accum = one_step(batch_size=16, accum=4)   # 4 microbatches of 4
    p_big = one_step(batch_size=16, accum=1)     # one batch of 16
    for a, b in zip(jax.tree_util.tree_leaves(p_accum),
                    jax.tree_util.tree_leaves(p_big)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_bf16_precision_casts_batch(seed):
    """Trainer(precision="bf16") must deliver bfloat16 floating inputs
    to the step (integer leaves untouched)."""
    seen = {}

    class Probe(LightningModule):
        batch_size = 8

        def configure_model(self):
            import flax.linen as nn
            return nn.Dense(2)

        def configure_optimizers(self):
            import optax
            return optax.sgd(0.01)

        def training_step(self, ctx, batch):
            x, y = batch
            seen["x"] = x.dtype
            seen["y"] = y.dtype
            loss = (ctx.apply(x.astype(jnp.float32)) ** 2).mean()
            ctx.log("loss", loss)
            return loss

        def train_dataloader(self):
            from ray_lightning_tpu.core.data import ArrayDataset, DataLoader
            x = np.zeros((16, 4), np.float32)
            y = np.zeros((16,), np.int32)
            return DataLoader(ArrayDataset(x, y), batch_size=8,
                              drop_last=True)

    t = Trainer(max_steps=1, max_epochs=1, enable_checkpointing=False,
                num_sanity_val_steps=0, limit_val_batches=0, seed=0,
                precision="bf16", log_every_n_steps=1)
    t.fit(Probe())
    assert seen["x"] == jnp.bfloat16
    assert seen["y"] == jnp.int32


def test_grad_clipping_with_zero1(seed):
    t = _fit(strategy="zero1", gradient_clip_val=0.5)
    assert t.global_step == 4
