"""Comm plane: blockwise quantization, compressed collectives, policy
resolution, error-feedback convergence, and the env-knob A/B — all on
the 8-virtual-device CPU mesh.

The HLO-level guarantees (compressed programs carry the low-precision
dtype and ~4x fewer reduction bytes; policy-off is byte-identical) live
in tests/test_collective_audit.py; this file covers numerics and
plumbing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_lightning_tpu.comm import (
    CommPolicy,
    CommState,
    blockwise_dequantize,
    blockwise_quantize,
    build_grad_sync,
    compressed_psum,
)
from ray_lightning_tpu.comm.quant import payload_bytes
from ray_lightning_tpu.models import BoringModel
from ray_lightning_tpu.parallel.mesh import shard_map_compat
from ray_lightning_tpu.parallel.strategy import resolve_strategy

from tests.utils import get_trainer

WORLD = 8


# ---------------------------------------------------------------------------
# quantizer
# ---------------------------------------------------------------------------


def test_quantize_roundtrip_error_bound(seed):
    """Per-element error of the blockwise int8 round trip is bounded by
    half a quantization step: max|block| / (2 * 127)."""
    rng = np.random.default_rng(0)
    x = (rng.standard_normal((16, 256)) *
         10.0 ** rng.integers(-3, 3, size=(16, 1))).astype(np.float32)
    for bs in (32, 64, 128):
        q, s = blockwise_quantize(jnp.asarray(x), bs)
        dq = np.asarray(blockwise_dequantize(q, s, bs))
        err = np.abs(dq - x).reshape(16, 256 // bs, bs)
        bound = np.abs(x).reshape(16, 256 // bs, bs).max(-1) / (2 * 127)
        assert (err <= bound[..., None] + 1e-7).all(), bs


def test_quantize_zero_blocks_exact():
    q, s = blockwise_quantize(jnp.zeros((4, 64)), 64)
    assert np.asarray(s).max() == 0
    assert np.asarray(blockwise_dequantize(q, s, 64)).max() == 0


def test_stochastic_rounding_unbiased():
    """floor(x/s + u) averages to x/s over draws (the deterministic
    round pins every draw to the same nearest level)."""
    x = np.full((1, 64), 0.3, np.float32)
    x[0, -1] = 1.0                    # block max -> scale 1/127; the
    x = jnp.asarray(x)                # 0.3s land between levels
    vals = []
    for i in range(300):
        qi, si = blockwise_quantize(x, 64, stochastic=True,
                                    rng=jax.random.PRNGKey(i))
        vals.append(float(np.asarray(
            blockwise_dequantize(qi, si, 64))[0, :-1].mean()))
    assert np.std(vals) > 0          # actually stochastic
    assert abs(np.mean(vals) - 0.3) < 0.002   # and unbiased


def test_payload_bytes_model():
    assert payload_bytes(1024, "int8", 64) == 1024 + 4 * 16
    assert payload_bytes(1024, "bf16") == 2048
    assert payload_bytes(1000, "int8", 64) == 1000 + 4 * 16  # ceil blocks
    assert payload_bytes(1024, "fp8", 64) == 1024 + 4 * 16   # 1 byte/elem
    assert payload_bytes(1024, "int4", 64) == 512 + 4 * 16   # 2 elem/byte
    assert payload_bytes(1001, "int4", 64) == 501 + 4 * 16   # ceil pack


# -- fp8 / int4 codecs ------------------------------------------------------


def test_fp8_roundtrip_error_bound(seed):
    """e4m3's per-element error is RELATIVE: half an ulp at 3 mantissa
    bits, <= max|block| / 16 after the block scaling maps the max to
    448."""
    from ray_lightning_tpu.comm.quant import compress_cast, decompress_cast
    rng = np.random.default_rng(3)
    x = (rng.standard_normal((16, 256)) *
         10.0 ** rng.integers(-3, 3, size=(16, 1))).astype(np.float32)
    q, s = compress_cast(jnp.asarray(x), "fp8")
    assert np.asarray(q).dtype == np.uint8      # 1-byte wire everywhere
    dq = np.asarray(decompress_cast(q, s, "fp8"))
    err = np.abs(dq - x).reshape(16, 4, 64)
    bound = np.abs(x).reshape(16, 4, 64).max(-1) / 16
    assert (err <= bound[..., None] + 1e-7).all()


def test_int4_roundtrip_error_bound_and_packing(seed):
    """int4: payload is HALF the element count (two nibbles per byte),
    error bounded by half a step: max|block| / (2 * 7)."""
    from ray_lightning_tpu.comm.quant import compress_cast, decompress_cast
    rng = np.random.default_rng(4)
    x = rng.standard_normal((16, 256)).astype(np.float32)
    q, s = compress_cast(jnp.asarray(x), "int4")
    assert np.asarray(q).shape == (16, 128)
    assert np.asarray(q).dtype == np.uint8
    dq = np.asarray(decompress_cast(q, s, "int4"))
    err = np.abs(dq - x).reshape(16, 4, 64)
    bound = np.abs(x).reshape(16, 4, 64).max(-1) / 14
    assert (err <= bound[..., None] + 1e-7).all()


@pytest.mark.parametrize("mode,tol", [("fp8", 0.002), ("int4", 0.004)])
def test_stochastic_rounding_unbiased_new_codecs(mode, tol):
    """The new codecs' SR averages to the true value over draws: int4
    via the same floor(x/s + u) as int8; fp8 via exact two-neighbor
    grid rounding (E[q] == x by construction)."""
    from ray_lightning_tpu.comm.quant import compress_cast, decompress_cast
    x = np.full((1, 64), 0.3, np.float32)
    x[0, -1] = 1.0
    x = jnp.asarray(x)
    vals = []
    for i in range(300):
        qi, si = compress_cast(x, mode, stochastic=True,
                               rng=jax.random.PRNGKey(i))
        vals.append(float(np.asarray(
            decompress_cast(qi, si, mode))[0, :-1].mean()))
    assert np.std(vals) > 0
    assert abs(np.mean(vals) - 0.3) < tol, np.mean(vals)


# ---------------------------------------------------------------------------
# compressed collectives (numerics under shard_map)
# ---------------------------------------------------------------------------


def _mesh():
    return resolve_strategy("ddp").build_mesh()


PSUM_TOL = {"int8": 0.02, "bf16": 0.01, "fp8": 0.1, "int4": 0.12}


@pytest.mark.parametrize("mode", ["int8", "bf16", "fp8", "int4"])
def test_compressed_psum_matches_mean(mode, seed):
    mesh = _mesh()
    rng = np.random.default_rng(1)
    x = rng.standard_normal((WORLD, 501)).astype(np.float32)

    def body(xl):
        return compressed_psum(xl[0], "data", WORLD, mode=mode,
                               mean=True)[None]

    fn = shard_map_compat(body, mesh, in_specs=P("data"),
                          out_specs=P("data"))
    xg = jax.device_put(x, NamedSharding(mesh, P("data")))
    out = np.asarray(jax.jit(fn)(xg))
    ref = x.mean(0)
    # every rank must hold the SAME reduced value (replicated result)
    assert np.allclose(out, out[0][None], atol=0)
    assert np.abs(out[0] - ref).max() <= PSUM_TOL[mode] * np.abs(x).max()


@pytest.mark.parametrize("mode", ["int8", "fp8", "int4"])
def test_hierarchical_psum_matches_mean(mode, seed):
    """Two-level (ici4 x dcn2) mean over the 8-way axis: replicated
    result within the flat path's tolerance (only one quantization —
    of the ICI-summed shard — happens at all), and the level-2 error
    term is per-rank chunk-local (each rank's residual support is its
    own 1/ici slice)."""
    from ray_lightning_tpu.comm import hierarchical_psum

    mesh = _mesh()
    rng = np.random.default_rng(5)
    x = rng.standard_normal((WORLD, 501)).astype(np.float32)

    def body(xl):
        res, err = hierarchical_psum(xl[0], "data", 4, 2, mode=mode,
                                     mean=True, with_error=True)
        return res[None], err[None]

    fn = shard_map_compat(body, mesh, in_specs=P("data"),
                          out_specs=(P("data"), P("data")))
    xg = jax.device_put(x, NamedSharding(mesh, P("data")))
    out, err = jax.jit(fn)(xg)
    out, err = np.asarray(out), np.asarray(err)
    ref = x.mean(0)
    assert np.allclose(out, out[0][None], atol=0)
    assert np.abs(out[0] - ref).max() <= PSUM_TOL[mode] * np.abs(x).max()
    # error support: every rank carries SOME error, only on its chunk
    # (ranks sharing a host quantize disjoint slices of the host sum)
    assert (np.abs(err).max(axis=1) > 0).all()
    chunk = 128     # ceil(501 / 4) rounded up to the 64-elem block
    for r in range(WORLD):
        local = r % 4
        outside = np.concatenate(
            [err[r, :local * chunk], err[r, (local + 1) * chunk:]])
        assert outside.size and np.abs(outside).max() == 0, r


def test_compressed_psum_error_feedback_term(seed):
    """with_error returns exactly x − dq(q(x)) — the residual error
    feedback re-injects next step."""
    mesh = _mesh()
    rng = np.random.default_rng(2)
    x = rng.standard_normal((WORLD, 130)).astype(np.float32)

    def body(xl):
        res, err = compressed_psum(xl[0], "data", WORLD, mode="int8",
                                   mean=True, with_error=True)
        return res[None], err[None]

    fn = shard_map_compat(body, mesh, in_specs=P("data"),
                          out_specs=(P("data"), P("data")))
    xg = jax.device_put(x, NamedSharding(mesh, P("data")))
    _, err = jax.jit(fn)(xg)
    err = np.asarray(err)
    # the error is per-rank local and bounded by half a quant step
    step = np.abs(x).max() / 127
    assert np.abs(err).max() <= step / 2 + 1e-6
    assert np.abs(err).max() > 0      # int8 on gaussians is never exact


# ---------------------------------------------------------------------------
# policy resolution
# ---------------------------------------------------------------------------


def test_policy_resolution_per_strategy():
    """The per-strategy decision table: replicated-param data-parallel
    strategies compress, param-sharded ones decline, off is inert."""
    pol = CommPolicy(compress="int8", axes=("data",))
    for name, expect in (("ddp", True), ("zero1", True),
                         ("fsdp", False), ("spmd", False)):
        strat = resolve_strategy(name)
        mesh = strat.build_mesh()
        sync = build_grad_sync(strat, mesh, pol)
        assert (sync is not None) == expect, name
        assert build_grad_sync(strat, mesh, CommPolicy()) is None, name
    from ray_lightning_tpu.parallel.pipeline import PipelineStrategy
    ps = PipelineStrategy(stages=2)
    assert build_grad_sync(ps, ps.build_mesh(), pol) is None


def test_policy_axis_resolution():
    strat = resolve_strategy("ddp")
    mesh = strat.build_mesh()
    # explicit axes: compressed regardless of process count
    pol = CommPolicy(compress="int8", axes=("data",))
    assert pol.resolved_axes(mesh, strat.data_axis_names) == ("data",)
    # unknown axes fall away
    pol = CommPolicy(compress="int8", axes=("dcn",))
    assert pol.resolved_axes(mesh, strat.data_axis_names) == ()
    # auto on a single process: all-ICI, nothing compresses (DCN default)
    pol = CommPolicy(compress="int8")
    assert pol.resolved_axes(mesh, strat.data_axis_names) == ()
    assert build_grad_sync(strat, mesh, pol) is None
    # single-device data axis cannot compress
    one = strat.build_mesh(devices=jax.devices()[:1])
    pol = CommPolicy(compress="int8", axes=("data",))
    assert build_grad_sync(strat, one, pol) is None


def test_policy_validation_and_resolve():
    with pytest.raises(ValueError):
        CommPolicy(compress="fp4")          # fp8/int4 ARE valid now
    with pytest.raises(ValueError):
        CommPolicy(param_gather="f64")
    with pytest.raises(ValueError):
        CommPolicy(compress="int4", block_size=33)   # odd: can't pack
    with pytest.raises(ValueError):
        CommPolicy(hierarchy=1)             # 0 / -1 / >= 2 only
    with pytest.raises(ValueError):
        CommPolicy(bucket_bytes=-1)
    assert CommPolicy.resolve("int8").compress == "int8"
    assert CommPolicy.resolve("fp8").compress == "fp8"
    assert CommPolicy.resolve({"compress": "int4"}).compress == "int4"
    assert not CommPolicy.resolve(None).enabled   # env-less default: off


def test_env_knobs_roundtrip(monkeypatch):
    from ray_lightning_tpu.comm.policy import HIER_AUTO
    src = CommPolicy(compress="fp8", axes=("data",), block_size=32,
                     stochastic_rounding=True, error_feedback=False,
                     param_gather="int8", hierarchy=4,
                     bucket_bytes=1 << 20, barrier_sync=True)
    for k, v in src.worker_env().items():
        monkeypatch.setenv(k, v)
    assert CommPolicy.resolve(None) == src
    monkeypatch.setenv("RLT_COMM_HIER", "auto")
    assert CommPolicy.resolve(None).hierarchy == HIER_AUTO


def test_hierarchy_resolution():
    """(ici, dcn) resolution: explicit sizes split when they divide,
    degenerate/invalid splits fall back to flat, auto follows the local
    device count (== world on the single-process CPU mesh: flat)."""
    from ray_lightning_tpu.comm.policy import HIER_AUTO
    pol = CommPolicy(compress="int8", hierarchy=4)
    assert pol.resolved_hierarchy(8) == (4, 2)
    assert pol.resolved_hierarchy(4) == (1, 4)    # 4 >= world: flat
    assert pol.resolved_hierarchy(6) == (1, 6)    # 6 % 4: flat
    flat = CommPolicy(compress="int8")
    assert flat.resolved_hierarchy(8) == (1, 8)
    auto = CommPolicy(compress="int8", hierarchy=HIER_AUTO)
    assert auto.resolved_hierarchy(WORLD) == (1, WORLD)


# ---------------------------------------------------------------------------
# end-to-end training (the documented parity tolerances)
# ---------------------------------------------------------------------------


def _fit_boring(tmp_path, tag, steps=20, comm_policy=None, **kw):
    trainer = get_trainer(str(tmp_path / tag), checkpoint=False,
                          max_epochs=100, limit_train_batches=10**6,
                          limit_val_batches=0, max_steps=steps, seed=0,
                          comm_policy=comm_policy, **kw)
    trainer.fit(BoringModel(lr=0.05, batch_size=16))
    return trainer, float(trainer.callback_metrics["loss"])


def test_error_feedback_convergence(tmp_path, seed):
    """Quantized DDP with error feedback matches the fp32 final loss on
    the boring model within the documented 5% tolerance after 20 steps
    (README "Compressed collectives")."""
    t_fp, loss_fp = _fit_boring(tmp_path, "fp32")
    assert t_fp._grad_sync is None
    pol = CommPolicy(compress="int8", axes=("data",))
    t_q, loss_q = _fit_boring(tmp_path, "int8", comm_policy=pol)
    assert t_q._grad_sync is not None
    assert isinstance(t_q.state.opt_state, CommState)
    # residual: [world, *param] leaves, sharded on data (dim 0)
    for leaf in jax.tree_util.tree_leaves(t_q.state.opt_state.residual):
        assert leaf.shape[0] == WORLD
        assert leaf.sharding.spec[0] == "data"
        assert np.abs(np.asarray(jax.device_get(leaf))).max() > 0
    assert abs(loss_q - loss_fp) <= 0.05 * max(loss_fp, 1e-6), (
        loss_q, loss_fp)


@pytest.mark.parametrize("mode", ["fp8", "int4"])
def test_new_codec_error_feedback_convergence(tmp_path, seed, mode):
    """fp8/int4 with error feedback land within the same documented 5%
    of the fp32 final loss as int8 (coarser grids, same EF guarantee:
    quantization error is a one-step delay, not a bias)."""
    _, loss_fp = _fit_boring(tmp_path, f"fp32_{mode}")
    pol = CommPolicy(compress=mode, axes=("data",))
    t_q, loss_q = _fit_boring(tmp_path, mode, comm_policy=pol)
    assert t_q._grad_sync is not None
    assert isinstance(t_q.state.opt_state, CommState)
    assert abs(loss_q - loss_fp) <= 0.05 * max(loss_fp, 1e-6), (
        loss_q, loss_fp)


def test_hierarchical_error_feedback_convergence(tmp_path, seed):
    """Two-level int8 (ici4 x dcn2 on the virtual mesh) trains within
    the 5% envelope; the residual keeps its [world, ...] layout (each
    rank's slice now supports only its 1/ici chunk of the DCN-stage
    error)."""
    _, loss_fp = _fit_boring(tmp_path, "fp32h")
    pol = CommPolicy(compress="int8", axes=("data",), hierarchy=4)
    t_q, loss_q = _fit_boring(tmp_path, "hier", comm_policy=pol)
    assert t_q._grad_sync is not None and t_q._grad_sync.hierarchical
    assert t_q._grad_sync.describe().endswith("/hier4x2")
    for leaf in jax.tree_util.tree_leaves(t_q.state.opt_state.residual):
        assert leaf.shape[0] == WORLD
    assert abs(loss_q - loss_fp) <= 0.05 * max(loss_fp, 1e-6), (
        loss_q, loss_fp)


def test_bucketed_sync_convergence_and_partition(tmp_path, seed):
    """Bucketed overlap scheduling: the greedy partition covers every
    leaf exactly once in order, and a bucketed fit (tiny target so the
    boring model actually splits) matches fp32 within the envelope —
    including the barrier_sync A/B variant, whose program differs only
    by the optimization_barrier."""
    from ray_lightning_tpu.comm import partition_buckets

    assert partition_buckets([100, 200, 4000, 50, 50], 300) \
        == [[0, 1], [2], [3, 4]]
    assert partition_buckets([10, 10], 0) == [[0], [1]]
    assert partition_buckets([1 << 30], 1024) == [[0]]

    _, loss_fp = _fit_boring(tmp_path, "fp32bkt")
    pol = CommPolicy(compress="int8", axes=("data",), bucket_bytes=2048)
    t_q, loss_q = _fit_boring(tmp_path, "bkt", comm_policy=pol)
    assert t_q._grad_sync is not None
    assert abs(loss_q - loss_fp) <= 0.05 * max(loss_fp, 1e-6)
    polb = CommPolicy(compress="int8", axes=("data",), bucket_bytes=2048,
                      barrier_sync=True)
    _, loss_b = _fit_boring(tmp_path, "bkt_barrier", comm_policy=polb)
    assert abs(loss_b - loss_fp) <= 0.05 * max(loss_fp, 1e-6)


def test_hierarchical_step_collective_bytes_split_by_link():
    """ddp/zero1 declare the hierarchical sync per link tier: the DCN
    ops carry the compressed 1/ici shard twice (rs + ag), the ICI ops
    the fp32 levels; declared_dcn_bytes extracts the slow-tier share
    for rlt_comm_dcn_bytes_total."""
    from ray_lightning_tpu.comm.audit import declared_dcn_bytes

    mesh = _mesh()
    pol = CommPolicy(compress="int8", axes=("data",), hierarchy=4)

    class _Leaf:
        shape = (1024,)
        dtype = np.dtype(np.float32)

    class _State:
        params = {"w": _Leaf()}

    ddp = resolve_strategy("ddp")
    sync = build_grad_sync(ddp, mesh, pol)
    d = ddp.step_collective_bytes(mesh, _State(), comm=sync)
    shard = 1024 // 4
    assert d["grad_all_reduce_dcn"] == 2 * payload_bytes(shard, "int8", 64)
    assert d["grad_all_reduce_ici"] == 4 * 1024 + 4 * 1024
    assert declared_dcn_bytes(d, multi_process=True) \
        == d["grad_all_reduce_dcn"]
    # flat declarations on a multi-process run: everything crosses DCN
    flat = ddp.step_collective_bytes(
        mesh, _State(),
        comm=build_grad_sync(ddp, mesh,
                             CommPolicy(compress="int8", axes=("data",))))
    assert declared_dcn_bytes(flat, True) == sum(flat.values())
    assert declared_dcn_bytes(flat, False) == 0
    z1 = resolve_strategy("zero1")
    z = z1.step_collective_bytes(mesh, _State(),
                                 comm=build_grad_sync(z1, mesh, pol))
    assert z["grad_sync_dcn"] == d["grad_all_reduce_dcn"]
    assert z["param_all_gather"] == 4096
    # the hierarchy's DCN declaration undercuts the flat one >= 2x
    assert 2 * d["grad_all_reduce_dcn"] <= sum(flat.values())


def test_bf16_mode_tracks_fp32_tighter(tmp_path, seed):
    _, loss_fp = _fit_boring(tmp_path, "fp32b")
    _, loss_bf = _fit_boring(
        tmp_path, "bf16", comm_policy=CommPolicy(compress="bf16",
                                                 axes=("data",)))
    assert abs(loss_bf - loss_fp) <= 0.01 * max(loss_fp, 1e-6)


def test_zero1_compressed_with_param_gather(tmp_path, seed):
    _, loss_fp = _fit_boring(tmp_path, "z1fp", strategy="zero1")
    pol = CommPolicy(compress="int8", axes=("data",), param_gather="bf16")
    _, loss_q = _fit_boring(tmp_path, "z1q", strategy="zero1",
                            comm_policy=pol)
    assert abs(loss_q - loss_fp) <= 0.05 * max(loss_fp, 1e-6)


def test_env_knob_ab(tmp_path, seed, monkeypatch):
    """RLT_COMM=int8 + RLT_COMM_AXES=data activates compression with no
    Trainer argument; unsetting it restores the fp32 path — same seed,
    both finite, within the documented tolerance of each other."""
    monkeypatch.setenv("RLT_COMM", "int8")
    monkeypatch.setenv("RLT_COMM_AXES", "data")
    t_on, loss_on = _fit_boring(tmp_path, "env_on", steps=8)
    assert t_on._grad_sync is not None
    assert t_on.comm_policy.compress == "int8"
    monkeypatch.delenv("RLT_COMM")
    monkeypatch.delenv("RLT_COMM_AXES")
    t_off, loss_off = _fit_boring(tmp_path, "env_off", steps=8)
    assert t_off._grad_sync is None
    assert np.isfinite(loss_on) and np.isfinite(loss_off)
    assert abs(loss_on - loss_off) <= 0.05 * max(loss_off, 1e-6)


def test_comm_metrics_report_compressed_bytes(tmp_path, seed):
    """step_collective_bytes shrinks to the compressed wire payload
    under an active policy — the series the metrics plane charges."""
    strat = resolve_strategy("zero1")
    mesh = strat.build_mesh()
    pol = CommPolicy(compress="int8", axes=("data",))
    sync = build_grad_sync(strat, mesh, pol)

    class _Leaf:
        shape = (1024,)
        dtype = np.dtype(np.float32)

    class _State:
        params = {"w": _Leaf()}

    fp = strat.step_collective_bytes(mesh, _State())
    q = strat.step_collective_bytes(mesh, _State(), comm=sync)
    assert fp["grad_reduce_scatter"] == 4096
    assert q["grad_reduce_scatter"] == payload_bytes(1024, "int8", 64)
    assert q["grad_all_gather"] == payload_bytes(1024, "int8", 64)
    assert q["param_all_gather"] == 4096       # param_gather="none"
    pol2 = CommPolicy(compress="int8", axes=("data",),
                      param_gather="bf16")
    sync2 = build_grad_sync(strat, mesh, pol2)
    q2 = strat.step_collective_bytes(mesh, _State(), comm=sync2)
    assert q2["param_all_gather"] == 2048
    # ddp: one all-reduce key at the rs+ag compressed payload
    ddp = resolve_strategy("ddp")
    qd = ddp.step_collective_bytes(mesh, _State(), comm=sync)
    assert qd["grad_all_reduce"] == 2 * payload_bytes(1024, "int8", 64)


def test_accumulation_composes_with_comm(tmp_path, seed):
    """k-microbatch accumulation inside the mapped region: one sync per
    optimizer step, same convergence envelope."""
    _, loss_fp = _fit_boring(tmp_path, "acc_fp", steps=8,
                             accumulate_grad_batches=2)
    _, loss_q = _fit_boring(
        tmp_path, "acc_q", steps=8, accumulate_grad_batches=2,
        comm_policy=CommPolicy(compress="int8", axes=("data",)))
    assert abs(loss_q - loss_fp) <= 0.05 * max(loss_fp, 1e-6)


def test_checkpoint_roundtrip_carries_residual(tmp_path, seed):
    """The CommState residual rides the msgpack checkpoint and restores
    into the sharded layout (resume continues, not restarts)."""
    pol = CommPolicy(compress="int8", axes=("data",))
    trainer = get_trainer(str(tmp_path / "save"), max_epochs=1,
                          limit_train_batches=4, limit_val_batches=0,
                          seed=0, comm_policy=pol)
    trainer.fit(BoringModel(lr=0.05, batch_size=16))
    ck = trainer.checkpoint_callback.best_model_path or \
        trainer.checkpoint_callback.last_model_path
    assert ck
    res_before = jax.device_get(trainer.state.opt_state.residual)
    t2 = get_trainer(str(tmp_path / "resume"), checkpoint=False,
                     max_epochs=2, limit_train_batches=4,
                     limit_val_batches=0, seed=0, comm_policy=pol,
                     resume_from_checkpoint=ck)
    t2.fit(BoringModel(lr=0.05, batch_size=16))
    assert t2.global_step > trainer.global_step
    res_after = jax.device_get(t2.state.opt_state.residual)
    for a, b in zip(jax.tree_util.tree_leaves(res_before),
                    jax.tree_util.tree_leaves(res_after)):
        assert np.asarray(a).shape == np.asarray(b).shape


def test_checkpoint_roundtrip_across_codec_change(tmp_path, seed):
    """A codec change between save and resume BRIDGES: every codec
    keeps the residual's [world, *param] layout, and an EF residual is
    codec-agnostic pending correction (x − dq(q(x)) in gradient units),
    so an int8 save resumes under fp8 — or under a hierarchical policy
    — carrying the saved residual forward (mirroring the PR-7
    comm-on↔off bridge rules: same-shape keeps, structure change drops
    with a warning, anything else raises naming the leaf)."""
    pol8 = CommPolicy(compress="int8", axes=("data",))
    trainer = get_trainer(str(tmp_path / "save"), max_epochs=1,
                          limit_train_batches=4, limit_val_batches=0,
                          seed=0, comm_policy=pol8)
    trainer.fit(BoringModel(lr=0.05, batch_size=16))
    ck = trainer.checkpoint_callback.best_model_path or \
        trainer.checkpoint_callback.last_model_path
    assert ck
    res_saved = jax.device_get(trainer.state.opt_state.residual)
    for tag, pol in (
            ("fp8", CommPolicy(compress="fp8", axes=("data",))),
            ("hier", CommPolicy(compress="int8", axes=("data",),
                                hierarchy=4))):
        t2 = get_trainer(str(tmp_path / f"resume_{tag}"),
                         checkpoint=False, max_epochs=2,
                         limit_train_batches=4, limit_val_batches=0,
                         seed=0, comm_policy=pol,
                         resume_from_checkpoint=ck)
        t2.fit(BoringModel(lr=0.05, batch_size=16))
        assert t2.global_step > trainer.global_step
        assert isinstance(t2.state.opt_state, CommState)
        for a, b in zip(
                jax.tree_util.tree_leaves(res_saved),
                jax.tree_util.tree_leaves(
                    jax.device_get(t2.state.opt_state.residual))):
            assert np.asarray(a).shape == np.asarray(b).shape
        assert np.isfinite(float(t2.callback_metrics["loss"]))


def test_stochastic_rounding_trains(tmp_path, seed):
    pol = CommPolicy(compress="int8", axes=("data",),
                     stochastic_rounding=True)
    _, loss = _fit_boring(tmp_path, "sr", steps=8, comm_policy=pol)
    assert np.isfinite(loss)
