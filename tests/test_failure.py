"""Failure surfacing: a DEAD worker (hard process exit, no exception)
must fail the fit with a real error on the driver, never hang.

Reference behavior (SURVEY.md §5): no elastic recovery — a worker crash
surfaces as a raised ``ray.get`` error in ``process_results``
(util.py:61-63) and fails the whole fit.  The raising-worker variant is
covered in test_plugin_distributed.py::test_worker_failure_raises_on_driver;
this file covers the harsher kill-without-cleanup mode and driver
reusability afterwards.
"""

import logging
import os
import signal
import threading
import time

import pytest

from ray_lightning_tpu import Callback, Trainer
from ray_lightning_tpu.models import BoringModel

from tests.utils import cpu_plugin


def _trainer(cb):
    return Trainer(max_epochs=1, limit_train_batches=4, limit_val_batches=0,
                   num_sanity_val_steps=0, enable_checkpointing=False,
                   callbacks=[cb], plugins=[cpu_plugin(2)], seed=0,
                   log_every_n_steps=1)


def _fit_must_raise_within(trainer, module, timeout_s):
    """Watchdog: the fit must RAISE within the window — a driver that
    blocks forever on a dead worker's future is this test's failure
    mode, so a wedge fails attributably instead of eating CI's budget."""
    box = {}

    def run():
        try:
            trainer.fit(module)
            box["outcome"] = "returned"
        except Exception as e:   # noqa: BLE001 - any error is a pass
            box["outcome"] = "raised"
            box["error"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout_s)
    assert not t.is_alive(), f"fit hung > {timeout_s}s on a dead worker"
    assert box.get("outcome") == "raised", "fit returned instead of raising"
    return box["error"]


def test_worker_hard_crash_raises_not_hangs():
    class DieInWorker(Callback):
        """Hard-kills the worker (no exception, no teardown)."""

        def on_train_batch_end(self, trainer, module, outputs, batch, idx):
            os._exit(17)

    _fit_must_raise_within(_trainer(DieInWorker()), BoringModel(), 240)


@pytest.mark.slow
def test_heartbeat_watchdog_names_wedged_rank(caplog):
    """A worker that stops making progress WITHOUT dying (SIGSTOP — the
    connection stays open, so no future errors) must be named by the
    driver's heartbeat watchdog within the timeout, instead of the fit
    hanging with zero explanation.  The process is then killed so the
    fit fails over the normal dead-worker path."""
    trainer = Trainer(
        max_epochs=1, limit_train_batches=64, limit_val_batches=0,
        num_sanity_val_steps=0, enable_checkpointing=False,
        callbacks=[], plugins=[cpu_plugin(2)], seed=0,
        log_every_n_steps=1,
        telemetry={"heartbeat_interval": 0.2, "heartbeat_timeout": 2.0})
    box = {}

    def run():
        try:
            trainer.fit(BoringModel(dataset_length=256))
            box["outcome"] = "returned"
        except Exception as e:   # noqa: BLE001
            box["outcome"] = "raised"
            box["error"] = e

    def beats_by_rank():
        agg = getattr(trainer.plugin, "_telemetry_agg", None)
        if agg is None:
            return {}
        return {v["beat"].get("rank"): v["beat"]
                for v in agg.heartbeats().values()}

    victim_pid = None
    with caplog.at_level(
            logging.WARNING,
            logger="ray_lightning_tpu.telemetry.aggregator"):
        t = threading.Thread(target=run, daemon=True)
        t.start()
        deadline = time.monotonic() + 120
        # wait for rank 1's heartbeats to reach the driver aggregator
        while time.monotonic() < deadline:
            beat = beats_by_rank().get(1)
            if beat is not None:
                victim_pid = beat["pid"]
                break
            time.sleep(0.05)
        assert victim_pid is not None, "rank 1 never heartbeat"
        os.kill(victim_pid, signal.SIGSTOP)
        try:
            # the watchdog must name the rank within the timeout window
            # (generous wall bound for CI; the configured timeout is 2s)
            found = None
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and found is None:
                for rec in caplog.records:
                    if "rank 1" in rec.message \
                            and "dead or wedged" in rec.message:
                        found = rec.message
                        break
                time.sleep(0.05)
        finally:
            os.kill(victim_pid, signal.SIGKILL)
        assert found, "watchdog never named the wedged rank"
        assert "last heartbeat" in found and "last span" in found
        t.join(240)
        assert not t.is_alive(), "fit hung after the worker was killed"
        assert box.get("outcome") == "raised"


def test_elastic_shrink_to_continue_matches_clean_resume(tmp_path):
    """THE elastic chaos proof (ISSUE 7 acceptance): a 2-worker run
    with RLT_ELASTIC snapshotting on loses rank 1 to an injected kill
    mid-run, shrinks to 1 worker WITHOUT a driver raise, and completes
    to max_steps — and its final parameters equal a clean 1-worker
    resume from the same snapshot (with the survivor's batch rescaled
    so the global batch is preserved, the clean run uses the doubled
    batch directly).  Tolerance: the 2-shard and 1-shard programs
    reduce the same global batch in different summation orders, so
    equality is allclose, not bitwise.

    With telemetry on, the death classification must also dump the
    killed rank's black box (ISSUE 9 acceptance): flight_1.json under
    the telemetry dir, naming rank 1, the classified cause, and its
    last spans (flush_every=1 so the kill cannot outrun the batch
    threshold)."""
    import jax
    import numpy as np
    from tests.conftest import assert_tree_allclose

    snap = str(tmp_path / "elastic")
    trainer = Trainer(
        max_epochs=20, max_steps=8, limit_val_batches=0,
        num_sanity_val_steps=0, enable_checkpointing=False, seed=0,
        log_every_n_steps=1, default_root_dir=str(tmp_path),
        plugins=[cpu_plugin(
            2, worker_env={"RLT_FAULT": "kill:rank=1,step=5"})],
        telemetry={"heartbeat_interval": 0.2, "flush_every": 1,
                   "metrics_interval": 0.5},
        elastic={"snapshot_every_n_steps": 2, "snapshot_dir": snap,
                 "max_restarts": 2})
    module = BoringModel(dataset_length=64, batch_size=2)
    trainer.fit(module)             # the kill must NOT raise here

    assert trainer.global_step == 8

    # -- crash flight recorder: the postmortem starts from evidence
    flight = os.path.join(str(tmp_path), "telemetry", "flight_1.json")
    assert os.path.exists(flight), \
        "death classification did not dump the killed rank's black box"
    import json
    doc = json.load(open(flight))
    assert doc["rank"] == 1
    assert "elastic death classification" in doc["cause"]
    assert "dead ranks [1]" in doc["cause"]
    names = {s["name"] for s in doc["spans"]}
    assert "step" in names, \
        f"flight dump missing the killed rank's last step spans: {names}"
    assert all(s.get("rank", 1) == 1 for s in doc["spans"])
    assert doc["heartbeats"], "no heartbeat trail in the black box"
    rep = trainer._elastic_report
    assert rep["restarts"] == 1
    assert rep["workers"] == 1 and rep["initial_workers"] == 2
    step = rep["resumed_step"]
    assert step is not None, "no durable snapshot to resume from"
    assert step < 8 and step % 2 == 0
    # the resumed segment kept snapshotting (bounded backpressure:
    # every cadence hit either saved or was counted as skipped)
    assert rep["snapshots"] + rep["skipped"] >= 1
    params_elastic = module._trained_variables["params"]

    # clean comparison: 1 worker resuming the SAME snapshot with the
    # doubled per-worker batch (2 workers x 2 == 1 worker x 4 — the
    # same global batches, so the trajectories must agree)
    module2 = BoringModel(dataset_length=64, batch_size=4)
    clean = Trainer(
        max_epochs=20, max_steps=8, limit_val_batches=0,
        num_sanity_val_steps=0, enable_checkpointing=False, seed=0,
        log_every_n_steps=1, default_root_dir=str(tmp_path / "clean"),
        plugins=[cpu_plugin(1)],
        resume_from_checkpoint=os.path.join(snap, str(step)))
    clean.fit(module2)
    assert clean.global_step == 8
    params_clean = module2._trained_variables["params"]
    assert_tree_allclose(params_elastic, params_clean)
    # and the run actually trained past the snapshot
    delta = sum(
        float(np.abs(np.asarray(a)).sum())
        for a in jax.tree_util.tree_leaves(params_elastic))
    assert delta > 0


def test_driver_usable_after_worker_failure():
    """After a failed distributed fit, the driver process can run a fresh
    (local) fit — no leaked global state."""

    class Boom(Callback):
        def on_train_start(self, trainer, module):
            raise RuntimeError("boom for reuse test")

    with pytest.raises(Exception, match="boom for reuse test"):
        _trainer(Boom()).fit(BoringModel())
    t = Trainer(max_epochs=1, limit_train_batches=2, limit_val_batches=0,
                num_sanity_val_steps=0, enable_checkpointing=False, seed=0)
    t.fit(BoringModel())
    assert t.global_step == 2
