"""Failure surfacing: a DEAD worker (hard process exit, no exception)
must fail the fit with a real error on the driver, never hang.

Reference behavior (SURVEY.md §5): no elastic recovery — a worker crash
surfaces as a raised ``ray.get`` error in ``process_results``
(util.py:61-63) and fails the whole fit.  The raising-worker variant is
covered in test_plugin_distributed.py::test_worker_failure_raises_on_driver;
this file covers the harsher kill-without-cleanup mode and driver
reusability afterwards.
"""

import logging
import os
import signal
import sys
import threading
import time

import cloudpickle
import pytest

from ray_lightning_tpu import Callback, Trainer
from ray_lightning_tpu.models import BoringModel

from tests.utils import cpu_plugin

# worker subprocesses cannot import this test module by name; ship the
# chaos fixture classes (AdamBoring) by value instead (the
# test_cluster_peer.py seam)
cloudpickle.register_pickle_by_value(sys.modules[__name__])


def _trainer(cb):
    return Trainer(max_epochs=1, limit_train_batches=4, limit_val_batches=0,
                   num_sanity_val_steps=0, enable_checkpointing=False,
                   callbacks=[cb], plugins=[cpu_plugin(2)], seed=0,
                   log_every_n_steps=1)


def _fit_must_raise_within(trainer, module, timeout_s):
    """Watchdog: the fit must RAISE within the window — a driver that
    blocks forever on a dead worker's future is this test's failure
    mode, so a wedge fails attributably instead of eating CI's budget."""
    box = {}

    def run():
        try:
            trainer.fit(module)
            box["outcome"] = "returned"
        except Exception as e:   # noqa: BLE001 - any error is a pass
            box["outcome"] = "raised"
            box["error"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout_s)
    assert not t.is_alive(), f"fit hung > {timeout_s}s on a dead worker"
    assert box.get("outcome") == "raised", "fit returned instead of raising"
    return box["error"]


def test_worker_hard_crash_raises_not_hangs():
    class DieInWorker(Callback):
        """Hard-kills the worker (no exception, no teardown)."""

        def on_train_batch_end(self, trainer, module, outputs, batch, idx):
            os._exit(17)

    _fit_must_raise_within(_trainer(DieInWorker()), BoringModel(), 240)


@pytest.mark.slow
def test_heartbeat_watchdog_names_wedged_rank(caplog):
    """A worker that stops making progress WITHOUT dying (SIGSTOP — the
    connection stays open, so no future errors) must be named by the
    driver's heartbeat watchdog within the timeout, instead of the fit
    hanging with zero explanation.  The process is then killed so the
    fit fails over the normal dead-worker path."""
    trainer = Trainer(
        max_epochs=1, limit_train_batches=64, limit_val_batches=0,
        num_sanity_val_steps=0, enable_checkpointing=False,
        callbacks=[], plugins=[cpu_plugin(2)], seed=0,
        log_every_n_steps=1,
        telemetry={"heartbeat_interval": 0.2, "heartbeat_timeout": 2.0})
    box = {}

    def run():
        try:
            trainer.fit(BoringModel(dataset_length=256))
            box["outcome"] = "returned"
        except Exception as e:   # noqa: BLE001
            box["outcome"] = "raised"
            box["error"] = e

    def beats_by_rank():
        agg = getattr(trainer.plugin, "_telemetry_agg", None)
        if agg is None:
            return {}
        return {v["beat"].get("rank"): v["beat"]
                for v in agg.heartbeats().values()}

    victim_pid = None
    with caplog.at_level(
            logging.WARNING,
            logger="ray_lightning_tpu.telemetry.aggregator"):
        t = threading.Thread(target=run, daemon=True)
        t.start()
        deadline = time.monotonic() + 120
        # wait for rank 1's heartbeats to reach the driver aggregator
        while time.monotonic() < deadline:
            beat = beats_by_rank().get(1)
            if beat is not None:
                victim_pid = beat["pid"]
                break
            time.sleep(0.05)
        assert victim_pid is not None, "rank 1 never heartbeat"
        os.kill(victim_pid, signal.SIGSTOP)
        try:
            # the watchdog must name the rank within the timeout window
            # (generous wall bound for CI; the configured timeout is 2s)
            found = None
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and found is None:
                for rec in caplog.records:
                    if "rank 1" in rec.message \
                            and "dead or wedged" in rec.message:
                        found = rec.message
                        break
                time.sleep(0.05)
        finally:
            os.kill(victim_pid, signal.SIGKILL)
        assert found, "watchdog never named the wedged rank"
        assert "last heartbeat" in found and "last span" in found
        t.join(240)
        assert not t.is_alive(), "fit hung after the worker was killed"
        assert box.get("outcome") == "raised"


def test_elastic_shrink_to_continue_matches_clean_resume(tmp_path):
    """THE elastic chaos proof (ISSUE 7 acceptance): a 2-worker run
    with RLT_ELASTIC snapshotting on loses rank 1 to an injected kill
    mid-run, shrinks to 1 worker WITHOUT a driver raise, and completes
    to max_steps — and its final parameters equal a clean 1-worker
    resume from the same snapshot (with the survivor's batch rescaled
    so the global batch is preserved, the clean run uses the doubled
    batch directly).  Tolerance: the 2-shard and 1-shard programs
    reduce the same global batch in different summation orders, so
    equality is allclose, not bitwise.

    With telemetry on, the death classification must also dump the
    killed rank's black box (ISSUE 9 acceptance): flight_1.json under
    the telemetry dir, naming rank 1, the classified cause, and its
    last spans (flush_every=1 so the kill cannot outrun the batch
    threshold)."""
    import jax
    import numpy as np
    from tests.conftest import assert_tree_allclose

    snap = str(tmp_path / "elastic")
    trainer = Trainer(
        max_epochs=20, max_steps=8, limit_val_batches=0,
        num_sanity_val_steps=0, enable_checkpointing=False, seed=0,
        log_every_n_steps=1, default_root_dir=str(tmp_path),
        plugins=[cpu_plugin(
            2, worker_env={"RLT_FAULT": "kill:rank=1,step=5"})],
        telemetry={"heartbeat_interval": 0.2, "flush_every": 1,
                   "metrics_interval": 0.5},
        elastic={"snapshot_every_n_steps": 2, "snapshot_dir": snap,
                 "max_restarts": 2})
    module = BoringModel(dataset_length=64, batch_size=2)
    trainer.fit(module)             # the kill must NOT raise here

    assert trainer.global_step == 8

    # -- crash flight recorder: the postmortem starts from evidence
    flight = os.path.join(str(tmp_path), "telemetry", "flight_1.json")
    assert os.path.exists(flight), \
        "death classification did not dump the killed rank's black box"
    import json
    doc = json.load(open(flight))
    assert doc["rank"] == 1
    assert "elastic death classification" in doc["cause"]
    assert "dead ranks [1]" in doc["cause"]
    names = {s["name"] for s in doc["spans"]}
    assert "step" in names, \
        f"flight dump missing the killed rank's last step spans: {names}"
    assert all(s.get("rank", 1) == 1 for s in doc["spans"])
    assert doc["heartbeats"], "no heartbeat trail in the black box"
    rep = trainer._elastic_report
    assert rep["restarts"] == 1
    assert rep["workers"] == 1 and rep["initial_workers"] == 2
    step = rep["resumed_step"]
    assert step is not None, "no durable snapshot to resume from"
    assert step < 8 and step % 2 == 0
    # the resumed segment kept snapshotting (bounded backpressure:
    # every cadence hit either saved or was counted as skipped)
    assert rep["snapshots"] + rep["skipped"] >= 1
    params_elastic = module._trained_variables["params"]

    # clean comparison: 1 worker resuming the SAME snapshot with the
    # doubled per-worker batch (2 workers x 2 == 1 worker x 4 — the
    # same global batches, so the trajectories must agree)
    module2 = BoringModel(dataset_length=64, batch_size=4)
    clean = Trainer(
        max_epochs=20, max_steps=8, limit_val_batches=0,
        num_sanity_val_steps=0, enable_checkpointing=False, seed=0,
        log_every_n_steps=1, default_root_dir=str(tmp_path / "clean"),
        plugins=[cpu_plugin(1)],
        resume_from_checkpoint=os.path.join(snap, str(step)))
    clean.fit(module2)
    assert clean.global_step == 8
    params_clean = module2._trained_variables["params"]
    assert_tree_allclose(params_elastic, params_clean)
    # and the run actually trained past the snapshot
    delta = sum(
        float(np.abs(np.asarray(a)).sum())
        for a in jax.tree_util.tree_leaves(params_elastic))
    assert delta > 0


class AdamBoring(BoringModel):
    """BoringModel with a real optimizer state (Adam moments) so the
    ZeRO-1 shard a dead rank takes with it is non-trivial — the thing
    parity redundancy exists to reconstruct."""

    def configure_optimizers(self):
        import optax
        return optax.adam(0.05)


def _chaos_trainer(tmp_path, snap, *, workers=2, fault=None, elastic=None,
                   max_steps=8, batch_size=2, resume=None, subdir=""):
    worker_env = {"RLT_FAULT": fault} if fault else None
    root = str(tmp_path / subdir) if subdir else str(tmp_path)
    return Trainer(
        max_epochs=20, max_steps=max_steps, limit_val_batches=0,
        num_sanity_val_steps=0, enable_checkpointing=False, seed=0,
        log_every_n_steps=1, default_root_dir=root,
        plugins=[cpu_plugin(workers, strategy="zero1",
                            worker_env=worker_env)],
        elastic=elastic, resume_from_checkpoint=resume)


def _clean_reference_params(tmp_path, stop_step, max_steps=8):
    """Final params of a fault-free run that mirrors a recovery resumed
    at ``stop_step``: 2 workers to ``stop_step`` (snapshotting every
    step), then 1 worker with the doubled batch to ``max_steps`` — the
    same global batches and the same epoch-replay-from-start semantics
    as any elastic resume."""
    snap = str(tmp_path / f"ref_snap_{stop_step}")
    m1 = AdamBoring(dataset_length=64, batch_size=2)
    _chaos_trainer(tmp_path, snap, max_steps=stop_step, subdir="ref1",
                   elastic={"snapshot_every_n_steps": 1,
                            "snapshot_dir": snap}).fit(m1)
    m2 = AdamBoring(dataset_length=64, batch_size=4)
    t2 = _chaos_trainer(tmp_path, snap, workers=1, max_steps=max_steps,
                        subdir="ref2",
                        resume=os.path.join(snap, str(stop_step)))
    t2.fit(m2)
    assert t2.global_step == max_steps
    return m2._trained_variables["params"]


def test_zero_replay_parity_recovery(tmp_path):
    """THE zero-replay proof (ISSUE 13 acceptance): a 2-worker ZeRO-1
    run with parity redundancy on loses rank 1 at step 5.  Durable
    snapshots exist only at steps 2/4 — yet the run resumes at step 5:
    the survivor's escrowed state plus the parity block reconstruct the
    dead optimizer shard in memory, the snapshot directory is never
    read (``snapshot_restores`` stays 0), and the final parameters
    equal the clean no-fault reference within the documented 2e-2 bar
    (observed: allclose at defaults — the escrow is a bit-exact host
    copy)."""
    from tests.conftest import assert_tree_allclose

    snap = str(tmp_path / "elastic")
    module = AdamBoring(dataset_length=64, batch_size=2)
    trainer = _chaos_trainer(
        tmp_path, snap, fault="kill:rank=1,step=5",
        elastic={"snapshot_every_n_steps": 2, "snapshot_dir": snap,
                 "max_restarts": 2, "redundancy": 1})
    trainer.fit(module)              # the kill must NOT raise here

    assert trainer.global_step == 8
    rep = trainer._elastic_report
    assert rep["recovery"] == "parity"
    assert rep["restarts"] == 1
    assert rep["workers"] == 1 and rep["initial_workers"] == 2
    # resumed PAST the last durable snapshot: with cadence 2 a replay
    # can only land on an even step — 5 proves in-memory state
    assert rep["resumed_step"] == 5
    # zero replay: no sharded restore ran anywhere in the fleet
    assert rep.get("snapshot_restores", 0) == 0
    assert rep.get("recovery_seconds", 0) > 0

    reference = _clean_reference_params(tmp_path, stop_step=5)
    assert_tree_allclose(module._trained_variables["params"], reference,
                         rtol=2e-2, atol=2e-2)


def test_same_fixture_with_redundancy_off_replays(tmp_path):
    """The PR 7 fallback still stands: identical fault, parity off —
    recovery routes to snapshot replay from step 4 and the restore
    counter shows exactly one replay."""
    from tests.conftest import assert_tree_allclose

    snap = str(tmp_path / "elastic")
    module = AdamBoring(dataset_length=64, batch_size=2)
    trainer = _chaos_trainer(
        tmp_path, snap, fault="kill:rank=1,step=5",
        elastic={"snapshot_every_n_steps": 2, "snapshot_dir": snap,
                 "max_restarts": 2})
    trainer.fit(module)

    assert trainer.global_step == 8
    rep = trainer._elastic_report
    assert rep["recovery"] == "replay"
    # the last DURABLE snapshot: step 4's async write may not have
    # committed before the kill, in which case step 2 is the truth
    assert rep["resumed_step"] in (2, 4)
    assert rep.get("snapshot_restores", 0) == 1
    reference = _clean_reference_params(tmp_path,
                                        stop_step=rep["resumed_step"])
    assert_tree_allclose(module._trained_variables["params"], reference,
                         rtol=2e-2, atol=2e-2)


def test_peerdrop_skips_parity_tick_without_failing(tmp_path):
    """Lossy-fabric chaos (tier-2 ``peerdrop``): rank 0 swallows the
    next inbound peer frame after step 2 — its step-3 parity exchange
    times out and is SKIPPED (previous escrow retained), the fleet
    never wedges, no restart happens, and later ticks resume."""
    snap = str(tmp_path / "elastic")
    module = AdamBoring(dataset_length=64, batch_size=2)
    trainer = Trainer(
        max_epochs=20, max_steps=8, limit_val_batches=0,
        num_sanity_val_steps=0, enable_checkpointing=False, seed=0,
        log_every_n_steps=1, default_root_dir=str(tmp_path),
        plugins=[cpu_plugin(
            2, strategy="zero1",
            worker_env={"RLT_FAULT": "peerdrop:rank=0,step=2,count=1",
                        "RLT_ELASTIC_PARITY_TIMEOUT_S": "2"})],
        elastic={"snapshot_every_n_steps": 4, "snapshot_dir": snap,
                 "max_restarts": 2, "redundancy": 1})
    trainer.fit(module)
    assert trainer.global_step == 8
    rep = trainer._elastic_report
    assert rep["restarts"] == 0
    assert rep.get("parity_skipped", 0) >= 1     # the dropped exchange
    assert rep.get("parity_ticks", 0) >= 5       # and the recovery after


@pytest.mark.slow
@pytest.mark.parametrize("scenario", ["kill_rank0", "cadence_boundary",
                                      "snapkill", "double_kill"])
def test_chaos_matrix(tmp_path, scenario):
    """The chaos matrix (ISSUE 13 satellite): every fault shape the
    tier-2 harness can express ends with a completed run whose params
    match the clean reference for its resume point within 2e-2 —
    parity for single-rank loss (including a death ON the snapshot
    cadence and a death INSIDE the async save), replay fallback for
    double loss and for the coordinator death that takes the whole
    fleet (and every escrow) with it."""
    from tests.conftest import assert_tree_allclose

    snap = str(tmp_path / "elastic")
    base = {"snapshot_every_n_steps": 2, "snapshot_dir": snap,
            "max_restarts": 2, "redundancy": 1}
    if scenario == "kill_rank0":
        # the driver-adjacent COORDINATOR rank dies (restart=0: a real
        # preemption does not deterministically repeat after a rewind).
        # Racy by nature: if rank 1 yields its escrow before its
        # jax.distributed client aborts, parity reconstructs rank 0;
        # if the coordinator death takes rank 1 (and its escrow) down
        # first, the driver must count ONE preemption and replay — it
        # must never refuse to recover
        trainer = _chaos_trainer(tmp_path, snap,
                                 fault="kill:rank=0,step=5,restart=0",
                                 elastic=base)
        expect_mode, expect_step, workers = None, 5, 2
    elif scenario == "cadence_boundary":
        # death exactly ON the snapshot cadence (step 4): the parity
        # escrow at step 4 must win over the same-step durable snapshot
        # (zero restores), not tie-break into a replay
        trainer = _chaos_trainer(tmp_path, snap,
                                 fault="kill:rank=1,step=4", elastic=base)
        expect_mode, expect_step, workers = "parity", 4, 2
    elif scenario == "snapkill":
        # rank 1 dies INSIDE its async step-4 save, before completing
        # its parity send for... step 4 already ticked (parity runs
        # before the snapshot), so parity still covers step 4 AND the
        # uncommitted step-4 snapshot must stay invisible to replay
        trainer = _chaos_trainer(tmp_path, snap,
                                 fault="snapkill:rank=1,step=4",
                                 elastic=base)
        expect_mode, expect_step, workers = "parity", 4, 2
    else:   # double_kill
        # two ranks die at once: parity (k=1) cannot cover them —
        # replay fallback from the last durable snapshot
        trainer = _chaos_trainer(
            tmp_path, snap, workers=3,
            fault="kill:rank=1,step=5;kill:rank=2,step=5",
            elastic=dict(base, max_restarts=2))
        expect_mode, expect_step, workers = "replay", 4, 3

    module = AdamBoring(dataset_length=64, batch_size=2)
    trainer.fit(module)
    assert trainer.global_step == 8
    rep = trainer._elastic_report
    if expect_mode is None:
        # coordinator-death race (see above): either route is a pass,
        # as long as the run completed and matches its own reference
        expect_mode = rep["recovery"]
        assert expect_mode in ("parity", "replay"), rep
    assert rep["recovery"] == expect_mode, rep
    if expect_mode == "parity":
        assert rep["resumed_step"] == expect_step, rep
        assert rep.get("snapshot_restores", 0) == 0
    else:
        # replay: the last DURABLE snapshot — the cadence hit nearest
        # the kill may not have committed before the process died
        assert rep["resumed_step"] in (2, expect_step), rep
        expect_step = rep["resumed_step"]
        assert rep.get("snapshot_restores", 0) == 1

    if workers == 2:
        reference = _clean_reference_params(tmp_path,
                                            stop_step=expect_step)
    else:
        # 3-worker double-kill mirror: 3 clean workers to the resume
        # step, then the lone survivor at the tripled batch to the end
        rsnap = str(tmp_path / "ref3")
        m1 = AdamBoring(dataset_length=64, batch_size=2)
        _chaos_trainer(tmp_path, rsnap, workers=3, max_steps=expect_step,
                       subdir="r3a",
                       elastic={"snapshot_every_n_steps": 1,
                                "snapshot_dir": rsnap}).fit(m1)
        m2 = AdamBoring(dataset_length=64, batch_size=6)
        t2 = _chaos_trainer(tmp_path, rsnap, workers=1, max_steps=8,
                            subdir="r3b",
                            resume=os.path.join(rsnap, str(expect_step)))
        t2.fit(m2)
        reference = m2._trained_variables["params"]
    assert_tree_allclose(module._trained_variables["params"],
                         reference, rtol=2e-2, atol=2e-2)


def test_driver_usable_after_worker_failure():
    """After a failed distributed fit, the driver process can run a fresh
    (local) fit — no leaked global state."""

    class Boom(Callback):
        def on_train_start(self, trainer, module):
            raise RuntimeError("boom for reuse test")

    with pytest.raises(Exception, match="boom for reuse test"):
        _trainer(Boom()).fit(BoringModel())
    t = Trainer(max_epochs=1, limit_train_batches=2, limit_val_batches=0,
                num_sanity_val_steps=0, enable_checkpointing=False, seed=0)
    t.fit(BoringModel())
    assert t.global_step == 2
