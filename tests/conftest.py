"""Test harness config.

Forces the CPU platform with 8 virtual XLA devices (the reference tests
against gloo on CPU CI runners the same way, SURVEY.md §4) BEFORE jax
initializes its backend.  Worker subprocesses spawned by distributed
tests get their platform via plugin env plumbing instead.
"""

import os

_REAL_HW = os.environ.get("CLUSTER") == "1"   # opt-in real-TPU session
                                              # (test_cluster_optin.py)

# Must happen before jax backend init: append the virtual-device flag.
_flags = os.environ.get("XLA_FLAGS", "")
if not _REAL_HW and "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if not _REAL_HW:
    jax.config.update("jax_platforms", "cpu")


def pytest_collection_modifyitems(config, items):
    """Under CLUSTER=1 only the opt-in real-hardware tests run: the rest
    of the suite assumes the 8-virtual-device CPU platform this session
    deliberately did not force."""
    if not _REAL_HW:
        return
    import pytest as _pytest
    skip = _pytest.mark.skip(
        reason="CLUSTER=1 session runs only opt-in real-hardware tests")
    for item in items:
        if "test_cluster_optin" not in str(item.fspath):
            item.add_marker(skip)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from ray_lightning_tpu.utils.seed import seed_everything  # noqa: E402


@pytest.fixture
def seed():
    seed_everything(0)


@pytest.fixture
def tmp_root(tmp_path):
    return str(tmp_path)


def assert_tree_allclose(a, b, rtol=1e-5, atol=1e-6):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=rtol, atol=atol)
