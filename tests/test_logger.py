"""CSV metrics logging (PL CSVLogger analog): metrics.csv written under
the trainer root, rank-zero-gated in distributed fits, disabled with
logger=False, custom loggers pluggable."""

import csv
import os
import pickle

from ray_lightning_tpu import Trainer
from ray_lightning_tpu.models import BoringModel
from ray_lightning_tpu.utils.logger import CSVLogger

from tests.utils import cpu_plugin


def _read(path):
    with open(path) as f:
        return list(csv.DictReader(f))


def test_csv_logger_unions_columns(tmp_path):
    lg = CSVLogger(str(tmp_path))
    lg.log_metrics({"loss": 1.0}, step=1)
    lg.log_metrics({"loss": 0.5, "val_loss": 0.7}, step=2)
    rows = _read(lg.path)
    assert rows[0]["loss"] == "1.0" and rows[0]["val_loss"] == ""
    assert rows[1]["val_loss"] == "0.7"


def test_fit_writes_metrics_csv(tmp_path, seed):
    trainer = Trainer(max_epochs=2, limit_train_batches=4,
                      limit_val_batches=2, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      log_every_n_steps=2,
                      default_root_dir=str(tmp_path))
    trainer.fit(BoringModel())
    path = os.path.join(str(tmp_path), "logs", "metrics.csv")
    assert os.path.exists(path)
    rows = _read(path)
    assert any(r.get("loss") for r in rows)
    assert any(r.get("val_loss") for r in rows)  # eval metrics logged too


def test_logger_false_writes_nothing(tmp_path, seed):
    trainer = Trainer(max_epochs=1, limit_train_batches=2,
                      limit_val_batches=0, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0, logger=False,
                      default_root_dir=str(tmp_path))
    trainer.fit(BoringModel())
    assert not os.path.exists(os.path.join(str(tmp_path), "logs"))


def test_custom_logger_object(tmp_path, seed):
    class Capture:
        def __init__(self):
            self.events = []

        def log_metrics(self, metrics, step):
            self.events.append((step, dict(metrics)))

    cap = Capture()
    trainer = Trainer(max_epochs=1, limit_train_batches=4,
                      limit_val_batches=0, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0, logger=cap,
                      log_every_n_steps=1,
                      default_root_dir=str(tmp_path))
    trainer.fit(BoringModel())
    assert len(cap.events) >= 4
    assert all("loss" in m for _s, m in cap.events[:4])


def test_distributed_fit_rank_zero_writes(tmp_path, seed):
    """With actors, rank 0's worker writes the CSV (shared FS here);
    the file exists and has training rows."""
    trainer = Trainer(max_epochs=1, limit_train_batches=4,
                      limit_val_batches=1, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      log_every_n_steps=2,
                      plugins=[cpu_plugin(2)],
                      default_root_dir=str(tmp_path))
    trainer.fit(BoringModel())
    path = os.path.join(str(tmp_path), "logs", "metrics.csv")
    assert os.path.exists(path)
    assert any(r.get("loss") for r in _read(path))


def test_fit_then_validate_preserves_file(tmp_path, seed):
    """A second dispatch (fresh pickled logger state) must append to the
    run's metrics.csv, not truncate it."""
    model = BoringModel()
    trainer = Trainer(max_epochs=1, limit_train_batches=4,
                      limit_val_batches=1, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      log_every_n_steps=2,
                      default_root_dir=str(tmp_path))
    trainer.fit(model)
    path = os.path.join(str(tmp_path), "logs", "metrics.csv")
    rows_after_fit = len(_read(path))
    assert rows_after_fit > 0
    # a pickled copy of the run's logger (what a second dispatch actually
    # ships, plugins/xla.py) continues the same file: fresh _started
    # state, same _run_id
    fresh = pickle.loads(pickle.dumps(trainer.logger))
    fresh._started = False
    fresh._fields = ["step"]
    fresh.log_metrics({"extra_metric": 1.0}, step=99)
    rows = _read(path)
    assert len(rows) == rows_after_fit + 1      # appended, not truncated
    assert rows[-1]["extra_metric"] == "1.0"
    assert any(r.get("loss") for r in rows)     # old rows intact


def test_new_run_truncates_stale_file(tmp_path):
    """A brand-new logger pointed at a dir holding another run's
    metrics.csv starts fresh instead of appending to the stale file."""
    old = CSVLogger(str(tmp_path))
    old.log_metrics({"loss": 1.0}, step=0)
    old.log_metrics({"loss": 0.5}, step=1)
    path = os.path.join(str(tmp_path), "logs", "metrics.csv")
    assert len(_read(path)) == 2

    new = CSVLogger(str(tmp_path))            # different run id
    new.log_metrics({"acc": 0.9}, step=0)
    rows = _read(path)
    assert len(rows) == 1                     # truncated, not appended
    assert rows[0]["acc"] == "0.9"
    assert "loss" not in rows[0]
