"""Planner plane (ray_lightning_tpu/plan/): enumeration, cost-model
scoring, top-k AOT verification, and ``Trainer(strategy="auto")``
end-to-end — plus the model-drift guard pinning each strategy's
declared ``step_collective_bytes`` against the audited HLO wire bytes
of its actually-lowered train step, so the planner's inputs can't
silently rot.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from ray_lightning_tpu.comm import CommPolicy
from ray_lightning_tpu.comm.audit import total_wire_bytes
from ray_lightning_tpu.compile import cache as compile_cache
from ray_lightning_tpu.core.steps import build_init_fn, build_train_step
from ray_lightning_tpu.models.boring import BoringModel
from ray_lightning_tpu.plan import (Candidate, PlanConfig, Planner,
                                    clear_plan_memo, enumerate_candidates,
                                    estimate_candidate)
from ray_lightning_tpu.parallel.strategy import resolve_strategy

BATCH = 16


def _boring():
    module = BoringModel(batch_size=BATCH, dataset_length=4 * BATCH)
    module.prepare_data()
    module.setup("fit")
    module.setup_model()
    return module


def _example_batch(module):
    return jax.tree_util.tree_map(
        np.asarray, next(iter(module.train_dataloader())))


# -- enumeration -----------------------------------------------------------

def test_enumeration_covers_inventory():
    cfg = PlanConfig(microbatch=(1, 2))
    cands, _ = enumerate_candidates(8, 16, cfg, process_count=2)
    by_strategy = {c.strategy for c in cands}
    assert by_strategy == {"ddp", "zero1", "fsdp", "spmd"}
    # spmd enumerates every data×fsdp divisor factorization
    assert {c.mesh_sizes["fsdp"] for c in cands if c.strategy == "spmd"} \
        == {2, 4, 8}
    # comm rides only the compressible strategies
    assert {c.strategy for c in cands if c.comm} == {"ddp", "zero1"}
    # donation and microbatch double the feasible combinations
    assert any(not c.donate for c in cands)
    assert any(c.microbatch == 2 for c in cands)
    # labels are unique (the report keys on them)
    labels = [c.label for c in cands]
    assert len(set(labels)) == len(labels)


def test_enumeration_prunes_with_named_reasons():
    cfg = PlanConfig(microbatch=(1, 4))
    # batch 8 over 8 shards: microbatch 4 cannot split 8/(8*4)
    _, pruned = enumerate_candidates(8, 8, cfg, process_count=2)
    reasons = {r.split(":")[0] for _, r in pruned}
    assert "microbatch_indivisible" in reasons, pruned
    assert "comm_unsupported" in reasons, pruned    # fsdp/spmd × comm
    # batch 12 cannot divide across 8 shards at all
    _, pruned12 = enumerate_candidates(8, 12, cfg, process_count=2)
    assert any(r.startswith("batch_indivisible") for _, r in pruned12)
    # single process: no DCN hop, comm pruned by name
    _, pruned1p = enumerate_candidates(8, 16, cfg, process_count=1)
    assert any(r.startswith("comm_no_dcn") for _, r in pruned1p)
    # every pruned entry names a candidate label AND a reason
    for label, reason in pruned + pruned12 + pruned1p:
        assert label and reason


# -- cost model ------------------------------------------------------------

def _fixture_scoring(strategy_name="ddp", donate=True, budget=None):
    module = _boring()
    batch = _example_batch(module)
    cand = Candidate(strategy=strategy_name, axis_sizes=(("data", 8),),
                     donate=donate)
    strategy = cand.build_strategy()
    mesh = strategy.build_mesh(batch_hint=BATCH)
    tx = module.configure_optimizers()
    abstract = jax.eval_shape(build_init_fn(module, tx),
                              jax.random.PRNGKey(0), batch)
    shardings = strategy.state_shardings(mesh, abstract)
    cfg = PlanConfig(hbm_budget_bytes=budget)
    batch_bytes = sum(np.asarray(x).nbytes
                      for x in jax.tree_util.tree_leaves(batch))
    return estimate_candidate(cand, strategy, mesh, abstract, shardings,
                              batch_bytes, cfg, process_count=1)


def test_over_budget_rejected_with_named_reason():
    est = _fixture_scoring(budget=1024)       # 1 KiB: nothing fits
    assert not est.fits
    assert est.reason.startswith("hbm_over_budget"), est.reason
    assert "MiB" in est.reason and "budget" in est.reason
    # a roomy budget accepts the same candidate
    assert _fixture_scoring(budget=1 << 30).fits


def test_undonated_peak_models_second_state_copy():
    donated = _fixture_scoring(donate=True, budget=1 << 30)
    undonated = _fixture_scoring(donate=False, budget=1 << 30)
    assert undonated.peak_bytes - donated.peak_bytes \
        == donated.state_bytes


def test_planner_raises_naming_reasons_when_nothing_fits():
    module = _boring()
    batch = _example_batch(module)
    planner = Planner(PlanConfig(hbm_budget_bytes=1024, topk=0))
    with pytest.raises(ValueError, match="hbm_over_budget"):
        planner.plan(module, batch, batch_hint=BATCH)


def test_ranking_deterministic_and_reports_everything():
    module = _boring()
    batch = _example_batch(module)
    r1 = Planner(PlanConfig(topk=0)).plan(module, batch, batch_hint=BATCH)
    r2 = Planner(PlanConfig(topk=0)).plan(module, batch, batch_hint=BATCH)
    d1, d2 = r1.to_dict(), r2.to_dict()
    assert d1["winner"] == d2["winner"]
    assert [e["label"] for e in d1["candidates"]] \
        == [e["label"] for e in d2["candidates"]]
    # every pruned/rejected entry carries its named reason
    for e in d1["candidates"]:
        if e["status"] in ("pruned", "rejected"):
            assert e["reason"], e
    # a tiny replicated model on a fast all-ICI mesh: DDP's single psum
    # beats the sharded strategies' gather traffic
    assert d1["winner"] == "ddp[data8]"


# -- top-k AOT verification (compile-cache counters) -----------------------

def test_topk_bounds_aot_compiles(tmp_path):
    module = _boring()
    batch = _example_batch(module)
    compile_cache.activate(compile_cache.CompileCacheConfig(
        enabled=True, dir=str(tmp_path / "cc")))
    try:
        compile_cache.reset_stats()
        report = Planner(PlanConfig(topk=2)).plan(module, batch,
                                                  batch_hint=BATCH)
        d = report.to_dict()
        assert d["compiled"] <= 2
        assert d["cache_misses"] <= 2, d["cache_misses"]
        assert d["winner"] is not None
        # re-planning the same shapes through the same cache compiles
        # nothing: every verify program is a disk hit
        report2 = Planner(PlanConfig(topk=2)).plan(module, batch,
                                                   batch_hint=BATCH)
        assert report2.to_dict()["cache_misses"] == 0
        assert report2.winner_label == report.winner_label
    finally:
        compile_cache.deactivate()
        compile_cache.reset_stats()


# -- strategy="auto" end-to-end --------------------------------------------

def _fit_trainer(tmp_path, name, **kw):
    from ray_lightning_tpu import Trainer
    return Trainer(
        default_root_dir=str(tmp_path / name), max_epochs=1,
        enable_checkpointing=False, num_sanity_val_steps=0,
        limit_val_batches=0, log_every_n_steps=10**9, seed=0, **kw)


def test_auto_end_to_end_matches_hand_picked(tmp_path, seed):
    """``strategy="auto"`` trains to completion and its final params
    equal the same plan hand-picked (BoringModel is deterministic:
    uses_rng=False, plain SGD)."""
    auto = _fit_trainer(tmp_path, "auto", strategy="auto", max_steps=4)
    m_auto = BoringModel(batch_size=BATCH, dataset_length=4 * BATCH)
    auto.fit(m_auto)
    assert auto.global_step == 4
    d = auto._plan_report
    assert d is not None and d["winner"] == "ddp[data8]"
    assert auto.strategy.name == "ddp"
    for e in d["candidates"]:
        if e["status"] in ("pruned", "rejected"):
            assert e["reason"], e

    hand = _fit_trainer(tmp_path, "hand", strategy="ddp", max_steps=4)
    m_hand = BoringModel(batch_size=BATCH, dataset_length=4 * BATCH)
    hand.fit(m_hand)
    assert hand._plan_report is None
    for a, b in zip(
            jax.tree_util.tree_leaves(m_auto._trained_variables),
            jax.tree_util.tree_leaves(m_hand._trained_variables)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_auto_end_to_end_two_workers(tmp_path, seed):
    """The acceptance leg: ``strategy="auto"`` on a 2-worker CPU mesh —
    every rank plans independently and deterministically, the fleet
    trains to max_steps in lockstep under the winner, rank-0's
    PlanReport rides back to the driver, and the result matches the
    same plan hand-picked."""
    from tests.utils import cpu_plugin

    auto = _fit_trainer(tmp_path, "auto", strategy="auto",
                        plugins=[cpu_plugin(2)])
    m_auto = BoringModel(batch_size=BATCH, dataset_length=4 * BATCH)
    auto.fit(m_auto)
    assert auto.global_step == 2      # 64 samples over 2 workers
    d = auto._plan_report
    assert d is not None and d["winner"] == "ddp[data2]"
    # param-sharded strategies' comm candidates pruned by name
    pruned = {e["label"]: e["reason"] for e in d["candidates"]
              if e["status"] == "pruned"}
    assert any(r.startswith("comm_unsupported") for r in pruned.values())

    hand = _fit_trainer(tmp_path, "hand", strategy="ddp",
                        plugins=[cpu_plugin(2)])
    m_hand = BoringModel(batch_size=BATCH, dataset_length=4 * BATCH)
    hand.fit(m_hand)
    for a, b in zip(
            jax.tree_util.tree_leaves(m_auto._trained_variables),
            jax.tree_util.tree_leaves(m_hand._trained_variables)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_auto_reuses_plan_inside_tune_trial(tmp_path):
    """Per-trial plan reuse: the second same-shaped plan inside a tune
    session is the memoized report (reused flag, zero compiles), and
    the report lands on the trial for post-hoc analysis."""
    from ray_lightning_tpu.tune.runner import Trial
    from ray_lightning_tpu.tune.session import TrialSession, set_session

    module = _boring()
    batch = _example_batch(module)
    clear_plan_memo()
    trial = Trial("t0", {}, str(tmp_path))
    set_session(TrialSession(trial, lambda t, m: None))
    try:
        r1 = Planner(PlanConfig(topk=0)).plan(module, batch,
                                              batch_hint=BATCH)
        assert not r1.reused
        r2 = Planner(PlanConfig(topk=0)).plan(module, batch,
                                              batch_hint=BATCH)
        assert r2.reused and r2.winner_label == r1.winner_label
        assert trial.plan_report is not None
        assert trial.plan_report["winner"] == r1.winner_label
        assert trial.plan_report["reused"]
    finally:
        set_session(None)
        clear_plan_memo()


# -- per-link scoring + measured-bandwidth calibration ---------------------

def test_link_gbps_per_op_attribution():
    """``_ici``-suffixed ops always score at ICI speed; everything else
    rides DCN exactly when the run spans processes — the attribution
    that keeps hierarchical candidates ranked right."""
    from ray_lightning_tpu.plan.cost import link_gbps

    cfg = PlanConfig(ici_gbps=100.0, dcn_gbps=10.0)
    assert link_gbps("grad_all_reduce_ici", cfg, 2) == 100.0
    assert link_gbps("grad_all_reduce_dcn", cfg, 2) == 10.0
    assert link_gbps("grad_all_reduce", cfg, 2) == 10.0
    assert link_gbps("grad_all_reduce_dcn", cfg, 1) == 100.0
    assert link_gbps("param_all_gather", cfg, 1) == 100.0


def test_hierarchical_candidate_scores_below_mischarged(seed):
    """A hierarchical GradSync declares ~8 bytes/element of fp32 ICI
    traffic; scoring it at per-link bandwidths must come out CHEAPER
    than the flat int8 candidate's all-DCN charge (the mis-ranking the
    per-op attribution exists to prevent)."""
    from ray_lightning_tpu.comm import build_grad_sync
    from ray_lightning_tpu.plan.candidates import policy_for_candidate

    module = _boring()
    batch = _example_batch(module)
    strat = resolve_strategy("ddp")
    mesh = strat.build_mesh(batch_hint=BATCH)
    tx = module.configure_optimizers()
    abstract = jax.eval_shape(build_init_fn(module, tx),
                              jax.random.PRNGKey(0), batch)
    shardings = strat.state_shardings(mesh, abstract)
    cfg = PlanConfig(ici_gbps=100.0, dcn_gbps=1.0)
    cand = Candidate(strategy="ddp", axis_sizes=(("data", 8),), comm=True)
    batch_bytes = sum(
        a.size * a.dtype.itemsize for a in jax.tree_util.tree_leaves(batch))

    def score(policy):
        sync = build_grad_sync(strat, mesh, policy)
        return estimate_candidate(cand, strat, mesh, abstract, shardings,
                                  batch_bytes, cfg, process_count=2,
                                  grad_sync=sync).comm_seconds

    flat = score(CommPolicy(compress="int8", axes=("data",)))
    hier = score(CommPolicy(compress="int8", axes=("data",), hierarchy=4))
    assert hier < flat, (hier, flat)
    # the planner's default comm-on candidate policy arms the hierarchy
    pol = policy_for_candidate(cand)
    assert pol.hierarchy != 0


def test_calibration_cache_roundtrip(tmp_path, monkeypatch):
    """RLT_PLAN_CALIBRATE=1: PlanConfig.resolve picks up measured link
    bandwidths, cached per topology fingerprint (second resolve reads
    the file); explicit RLT_PLAN_*_GBPS still wins."""
    import json

    from ray_lightning_tpu.comm import calibrate

    monkeypatch.setenv(calibrate.ENV_DIR, str(tmp_path))
    monkeypatch.setenv("RLT_PLAN_CALIBRATE", "1")
    cfg = PlanConfig.resolve(None)
    path = calibrate.cache_path()
    assert tmp_path.joinpath(path.split("/")[-1]).exists()
    data = json.loads(open(path).read())
    # the 8-virtual-device CPU mesh measures its ICI proxy; DCN has no
    # hop to measure and keeps the constant
    assert "ici" in data["measured"]
    assert cfg.ici_gbps == data["ici_gbps"] > 0
    assert cfg.dcn_gbps == data["dcn_gbps"]
    # cache hit: mutate the file, re-resolve, the mutated value is read
    data["ici_gbps"] = 123.456
    open(path, "w").write(json.dumps(data))
    assert PlanConfig.resolve(None).ici_gbps == 123.456
    # explicit env overrides beat calibration
    monkeypatch.setenv("RLT_PLAN_ICI_GBPS", "77.0")
    assert PlanConfig.resolve(None).ici_gbps == 77.0


# -- remat axis (PR 12): enumeration, ranking, hand-measured picks ---------

def _gpt(name="tiny", batch_size=BATCH):
    from ray_lightning_tpu.models.gpt import GPTLightningModule
    module = GPTLightningModule(name, dataset_size=4 * batch_size,
                                batch_size=batch_size)
    module.setup_model()
    return module


def test_remat_axis_enumeration_and_pruning():
    """A module with a configure_remat() ladder multiplies the
    candidate space by its policies; one without it keeps the PR-8
    space and records the requested-but-unsupported axis by name."""
    from ray_lightning_tpu.plan import resolve_remat_options

    module = _gpt()
    spec = module.configure_remat()
    assert spec is not None and spec.default == "off"
    options, pruned = resolve_remat_options(spec, PlanConfig())
    assert set(options) == {"off", "full", "dots", "dots_no_batch"}
    assert not pruned
    # restriction + unknown policy: known survive, unknown prunes by name
    options, pruned = resolve_remat_options(
        spec, PlanConfig(remat=("dots", "warp")))
    assert options == ("dots",)
    assert any(r.startswith("remat_unsupported") for _, r in pruned)
    # no ladder + explicit request -> named prune, axis collapses
    options, pruned = resolve_remat_options(None, PlanConfig(remat=("dots",)))
    assert options == ("",)
    assert any(r.startswith("remat_unsupported") for _, r in pruned)
    # the axis multiplies enumeration and labels carry the policy
    cands, _ = enumerate_candidates(8, BATCH, PlanConfig(),
                                    remat_options=("off", "dots"))
    by_remat = {c.remat for c in cands}
    assert by_remat == {"off", "dots"}
    assert any(c.label.endswith("rm-dots") for c in cands)
    labels = [c.label for c in cands]
    assert len(set(labels)) == len(labels)


def test_remat_env_pin_and_worker_round_trip(monkeypatch):
    """RLT_REMAT_POLICY pins the sweep to the forced policy (the model
    build would override every candidate anyway), ships driver→worker
    via the plugin env base, and the new RLT_PLAN_* remat knobs
    round-trip through PlanConfig.worker_env like the PR-8 set."""
    from ray_lightning_tpu.plan import resolve_remat_options
    from tests.utils import cpu_plugin

    spec = _gpt().configure_remat()
    monkeypatch.setenv("RLT_REMAT_POLICY", "dots")
    options, _ = resolve_remat_options(spec, PlanConfig())
    assert options == ("dots",)
    plugin = cpu_plugin(2)
    assert plugin._worker_env_base()["RLT_REMAT_POLICY"] == "dots"
    monkeypatch.delenv("RLT_REMAT_POLICY")
    assert "RLT_REMAT_POLICY" not in plugin._worker_env_base()
    # planner knob env round-trip (worker_env -> resolve reproduces)
    cfg = PlanConfig(remat=("dots", "off"), hbm_gbps=500.0,
                     device_tflops=90.0)
    for k, v in cfg.worker_env().items():
        monkeypatch.setenv(k, v)
    assert PlanConfig.resolve(None) == cfg


def test_remat_ranking_deterministic_and_reported():
    """The remat axis ranks deterministically, the tiny fixture's
    winner is the hand-measured ``off`` (no memory pressure; the
    modeled per-region overhead prices the recompute ladder out), and
    the report's ``remat`` field carries the per-policy modeled
    HBM/recompute deltas."""
    module = _gpt()
    batch = _example_batch(module)
    r1 = Planner(PlanConfig(topk=0)).plan(module, batch, batch_hint=BATCH)
    r2 = Planner(PlanConfig(topk=0)).plan(module, batch, batch_hint=BATCH)
    d1, d2 = r1.to_dict(), r2.to_dict()
    assert d1["winner"] == d2["winner"] == "ddp[data8]:rm-off"
    assert [e["label"] for e in d1["candidates"]] \
        == [e["label"] for e in d2["candidates"]]
    rm = d1["remat"]
    assert rm["winner"] == "off"
    assert set(rm["policies"]) == {"off", "full", "dots", "dots_no_batch"}
    for policy, row in rm["policies"].items():
        assert row["peak_bytes"] and row["remat_seconds"] is not None
    # the deltas the axis exists to expose: "off" saves everything
    # (max HBM, no recompute seconds beyond traffic), "full" saves
    # nothing (min HBM)
    pol = rm["policies"]
    assert pol["off"]["act_bytes"] > pol["dots"]["act_bytes"] \
        > pol["full"]["act_bytes"] == 0
    # planning applied nothing: the module still carries its default
    assert module.config.remat is False


@pytest.mark.parametrize("name,expected", [
    ("tiny", "off"),
    ("gpt2-medium", "dots"),
    ("gpt2-moe-8e", "dots"),
])
def test_cost_model_reproduces_hand_measured_picks(name, expected):
    """The acceptance pin: the cost model alone (topk=0 — nothing
    compiles) reproduces every hand-measured remat pick documented in
    models/gpt.py — tiny→off (recompute overhead loses, memory is
    free), gpt2-medium→dots (+17% steps/s measured walk), and
    gpt2-moe-8e→dots (beats BOTH full and off; the dots_moe* save
    lists rank below plain dots exactly as measured)."""
    module = _gpt(name, batch_size=8)
    batch = _example_batch(module)
    cfg = PlanConfig(topk=0, strategies=("ddp",),
                     hbm_budget_bytes=16 << 30)
    report = Planner(cfg).plan(module, batch, batch_hint=8)
    d = report.to_dict()
    assert d["remat"]["winner"] == expected, d["remat"]
    assert report.winner_candidate.remat == expected
    if name == "gpt2-moe-8e":
        pol = d["remat"]["policies"]
        assert pol["dots"]["remat_seconds"] \
            < pol["dots_moe_act"]["remat_seconds"] \
            < pol["dots_moe"]["remat_seconds"]


def test_auto_end_to_end_gpt_applies_remat_winner(tmp_path, seed):
    """strategy='auto' with a remat-capable module trains to
    completion, records the remat ladder in its report, and the final
    params equal the hand-picked equivalent plan (tiny's winner is the
    module default 'off', so the applied config is unchanged)."""
    from ray_lightning_tpu.models.gpt import GPTLightningModule

    def gpt_module():
        return GPTLightningModule("tiny", dataset_size=4 * BATCH,
                                  batch_size=BATCH)

    auto = _fit_trainer(tmp_path, "auto", strategy="auto",
                        plan={"topk": 0}, max_steps=3)
    m_auto = gpt_module()
    auto.fit(m_auto)
    assert auto.global_step == 3
    d = auto._plan_report
    assert d["winner"] == "ddp[data8]:rm-off"
    assert d["remat"]["winner"] == "off"
    assert m_auto.config.remat is False
    hand = _fit_trainer(tmp_path, "hand", strategy="ddp", max_steps=3)
    m_hand = gpt_module()
    hand.fit(m_hand)
    for a, b in zip(
            jax.tree_util.tree_leaves(m_auto._trained_variables),
            jax.tree_util.tree_leaves(m_hand._trained_variables)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)

    # a NON-default winner is applied in place: restricting the sweep
    # to "dots" must reconfigure the module (remat wrap on) and still
    # train to completion
    forced = _fit_trainer(tmp_path, "forced", strategy="auto",
                          plan={"topk": 0, "remat": ("dots",)},
                          max_steps=2)
    m_forced = gpt_module()
    assert m_forced.config.remat is False
    forced.fit(m_forced)
    assert forced.global_step == 2
    assert forced._plan_report["winner"] == "ddp[data8]:rm-dots"
    assert m_forced.config.remat is True
    assert m_forced.config.remat_policy == "dots"


# -- remat drift guard: modeled activation bytes vs compiled programs ------

@pytest.fixture(scope="module")
def remat_compiled_peaks():
    """Compile the tiny-GPT train step (single device, donated) under
    full / dots / off and yield each program's memory_analysis peak —
    the measured side of the activation-model drift guard."""
    from ray_lightning_tpu.models.gpt import GPTLightningModule

    peaks = {}
    for policy in ("full", "dots", "off"):
        module = GPTLightningModule("tiny", dataset_size=4 * BATCH,
                                    batch_size=BATCH)
        module.configure_remat().apply(policy)
        module.setup_model()
        batch = jax.tree_util.tree_map(
            np.asarray, next(iter(module.train_dataloader())))
        tx = module.configure_optimizers()
        abstract = jax.eval_shape(build_init_fn(module, tx),
                                  jax.random.PRNGKey(0), batch)
        jitted = jax.jit(build_train_step(module, tx), donate_argnums=0)
        mem = jitted.lower(abstract, batch).compile().memory_analysis()
        peaks[policy] = (int(mem.argument_size_in_bytes)
                         + int(mem.output_size_in_bytes)
                         + int(mem.temp_size_in_bytes)
                         - int(mem.alias_size_in_bytes))
    return peaks


def test_remat_drift_modeled_vs_compiled(remat_compiled_peaks):
    """The activation model can't silently rot: per policy, the
    modeled saved-activation bytes (core/remat.py probe through
    plan/cost.py remat_terms) must track the COMPILED programs'
    memory_analysis peak deltas vs the save-nothing baseline within a
    calibrated band (measured on this toolchain: off 1.05x, dots
    0.52x — the model lists residuals at their own dtype while XLA's
    buffer assignment shares buffers), and the modeled policy ordering
    must match the compiled one."""
    from ray_lightning_tpu.plan.cost import remat_terms

    module = _gpt()
    spec = module.configure_remat()
    batch = _example_batch(module)
    cfg = PlanConfig()
    modeled = {}
    for policy in ("full", "dots", "off"):
        probe = spec.probe(policy, batch)
        act, _seconds = remat_terms(probe, policy, cfg,
                                    process_count=1, dp=1, microbatch=1)
        modeled[policy] = act
    compiled = remat_compiled_peaks
    # ordering: more saved activations -> higher compiled peak
    assert modeled["off"] > modeled["dots"] > modeled["full"] == 0
    assert compiled["off"] > compiled["dots"] > compiled["full"]
    # calibrated bands on the deltas vs the save-nothing program
    for policy in ("dots", "off"):
        measured_delta = compiled[policy] - compiled["full"]
        ratio = modeled[policy] / measured_delta
        assert 0.2 <= ratio <= 4.0, (policy, modeled[policy],
                                     measured_delta)


# -- resolve_strategy surface (satellite: docstring/README drift) ----------

def test_resolve_strategy_unknown_name_lists_valid_set():
    with pytest.raises(ValueError) as ei:
        resolve_strategy("warpdrive")
    msg = str(ei.value)
    for name in ("ddp", "zero1", "fsdp", "spmd", "auto", "sharded"):
        assert name in msg, msg


def test_resolve_auto_returns_sentinel():
    auto = resolve_strategy("auto")
    assert auto.name == "auto"
    with pytest.raises(RuntimeError, match="planner"):
        auto.build_mesh()


# -- model-drift guard: declared bytes vs audited HLO ----------------------

#: drift legs: (strategy, key) -> CommPolicy (None = uncompressed).
#: False/True keep PR-8's flat keys; "hier"/"fp8" are the PR-10 paths.
_DRIFT_LEGS = (
    ("ddp", False, None),
    ("ddp", True, CommPolicy(compress="int8", axes=("data",))),
    ("zero1", False, None),
    ("zero1", True, CommPolicy(compress="int8", axes=("data",))),
    ("ddp", "hier", CommPolicy(compress="int8", axes=("data",),
                               hierarchy=4)),
    ("ddp", "fp8", CommPolicy(compress="fp8", axes=("data",))),
    ("zero1", "gather", CommPolicy(compress="int8", axes=("data",),
                                   gather_bucket_bytes=1 << 14)),
)


@pytest.fixture(scope="module")
def drift_programs():
    """Compile the REAL train step for every ``_DRIFT_LEGS`` entry on
    the 8-device mesh; yield declared step_collective_bytes next to
    the audited HLO wire bytes of the same lowered program."""
    from ray_lightning_tpu.models.gpt import GPTLightningModule

    out = {}
    for name, comm, policy in _DRIFT_LEGS:
        module = GPTLightningModule("tiny", dataset_size=4 * BATCH,
                                    batch_size=BATCH)
        module.setup_model()
        strat = resolve_strategy(name)
        mesh = strat.build_mesh(batch_hint=BATCH)
        sync = strat.grad_transform(mesh, policy) if comm else None
        tx = module.configure_optimizers()
        if sync is not None:
            tx = sync.wrap_tx(tx)
        batch = jax.tree_util.tree_map(
            np.asarray, next(iter(module.train_dataloader())))
        abstract = jax.eval_shape(build_init_fn(module, tx),
                                  jax.random.PRNGKey(0), batch)
        shardings = strat.state_shardings(mesh, abstract)
        if sync is not None:
            shardings = shardings.replace(
                opt_state=sync.fix_opt_shardings(
                    shardings.opt_state, abstract.opt_state))
        jitted = jax.jit(
            build_train_step(module, tx, grad_sync=sync),
            donate_argnums=0,
            in_shardings=(shardings,
                          strat.batch_shardings(mesh, batch)),
            out_shardings=(shardings, None))
        compiled = jitted.lower(abstract, batch).compile()
        out[(name, comm)] = {
            "declared": strat.step_collective_bytes(mesh, abstract,
                                                    comm=sync),
            "text": compiled.as_text(),
        }
    return out


def test_drift_ddp_uncompressed(drift_programs):
    """DDP declares one grad all-reduce the size of the (bf16-resident)
    params.  The audited program moves more: grads ride the wire at f32
    (2× the bf16 declaration — the partitioner resolves partial sums at
    the f32 grad dots, tests/test_collective_audit.py), the all-reduce
    wire factor is 2× (reduce-scatter + all-gather phases), and the
    partitioner inserts ~25% extra reductions beyond the logical grad
    sum — measured 4.96× on this toolchain.  The band pins that
    calibration: either side silently halving or doubling leaves it."""
    p = drift_programs[("ddp", False)]
    declared = sum(p["declared"].values())
    audited = total_wire_bytes(p["text"], axis_size=8,
                               ops=("all-reduce",))
    assert 3.5 <= audited / declared <= 6.5, (audited, declared)


def test_drift_zero1_uncompressed(drift_programs):
    """ZeRO-1 declares grad reduce-scatter + param all-gather (one
    params' worth each, at residency dtype).  Audited: the CPU lowering
    spells the grad phase as f32 all-reduce + dynamic-slice (see
    Zero1Strategy's docstring) and the param gather at the param dtype —
    measured 3.48× the declaration on this toolchain (same f32-wire ×
    all-reduce-factor composition as the DDP leg).  Band pins the
    calibration against silent 2× rot on either side."""
    p = drift_programs[("zero1", False)]
    declared = sum(p["declared"].values())
    audited = total_wire_bytes(
        p["text"], axis_size=8,
        ops=("all-reduce", "all-gather", "reduce-scatter"))
    assert 2.4 <= audited / declared <= 4.6, (audited, declared)


@pytest.mark.parametrize("name", ["ddp", "zero1"])
def test_drift_compressed_declaration_tracks_audit(drift_programs, name):
    """With comm=int8 the declaration IS the compressed wire payload
    (quant.payload_bytes) and the program's collectives are the comm
    plane's own manual lowering — so declared and audited agree far
    more tightly than the partitioner legs (measured 1.05× ddp, 1.51×
    zero1: the slack is ZeRO-1's uncompressed param gather riding
    partitioner spelling).  Also re-pins that the compressed program
    moves ≥2× fewer audited bytes than the flat one — the saving the
    planner's comm dimension exists to exploit."""
    comp = drift_programs[(name, True)]
    flat = drift_programs[(name, False)]
    declared_c = sum(comp["declared"].values())
    audited_c = total_wire_bytes(comp["text"], axis_size=8)
    audited_f = total_wire_bytes(flat["text"], axis_size=8)
    assert 0.7 <= audited_c / declared_c <= 2.0, (audited_c, declared_c)
    assert audited_c * 2.0 <= audited_f, (audited_c, audited_f)


def test_drift_hierarchical_per_link_attribution(drift_programs):
    """The hierarchical (ici4 x dcn2) declaration is split by link tier
    (``_dcn``/``_ici`` op suffixes) and BOTH sides must track the
    audited per-link HLO bytes: the DCN share against the host-crossing
    replica groups, the ICI share against the intra-host ones.  The
    manual lowering is the comm plane's own, so the bands are tight
    (same 0.7-2.0 calibration as the flat compressed legs) — a planner
    scoring hierarchical candidates from a declaration that silently
    stops splitting (or an audit that loses the groups) leaves them."""
    from ray_lightning_tpu.comm.audit import wire_bytes_by_link

    p = drift_programs[("ddp", "hier")]
    declared_dcn = sum(b for op, b in p["declared"].items()
                       if op.endswith("_dcn"))
    declared_ici = sum(b for op, b in p["declared"].items()
                       if op.endswith("_ici"))
    assert declared_dcn > 0 and declared_ici > 0, p["declared"]
    audited = wire_bytes_by_link(p["text"], ici_size=4, axis_size=8,
                                 ops=("all-to-all", "all-gather"))
    assert 0.7 <= audited["dcn"] / declared_dcn <= 2.0, (
        audited, declared_dcn)
    assert 0.7 <= audited["ici"] / declared_ici <= 2.0, (
        audited, declared_ici)
    # and the hierarchy's point: declared DCN bytes are >= 2x under the
    # flat int8 declaration's total (only the 1/ici shard crosses)
    flat_declared = sum(drift_programs[("ddp", True)]["declared"].values())
    assert 2 * declared_dcn <= flat_declared, (declared_dcn, flat_declared)


def test_drift_bucketed_gather_declaration_tracks_audit(drift_programs):
    """ZeRO-1 with the EXPLICIT bucketed updated-param gather
    (gather_bucket_bytes > 0): the declaration renames the gather op
    ``param_all_gather_bucketed`` at UNCHANGED bytes (the buckets move
    the same payload — only the dependence structure differs), the
    compiled program still tracks the same calibrated band as the plain
    compressed leg, and the planner's cost model discounts ONLY the
    bucketed op's seconds (BUCKETED_EXPOSED_FRACTION), never its
    bytes."""
    from ray_lightning_tpu.plan.cost import (
        BUCKETED_EXPOSED_FRACTION, op_overlap_factor)

    p = drift_programs[("zero1", "gather")]
    plain = drift_programs[("zero1", True)]
    assert "param_all_gather_bucketed" in p["declared"], p["declared"]
    assert "param_all_gather" not in p["declared"], p["declared"]
    assert p["declared"]["param_all_gather_bucketed"] == \
        plain["declared"]["param_all_gather"], (p["declared"],
                                                plain["declared"])
    declared = sum(p["declared"].values())
    audited = total_wire_bytes(p["text"], axis_size=8)
    assert 0.7 <= audited / declared <= 2.0, (audited, declared)
    # the cost model's declared-overlap discount: half the seconds on
    # the bucketed op, full price everywhere else
    assert op_overlap_factor(
        "param_all_gather_bucketed") == BUCKETED_EXPOSED_FRACTION
    assert op_overlap_factor("param_all_gather") == 1.0
    assert op_overlap_factor("grad_reduce_scatter") == 1.0


def test_drift_fp8_declaration_tracks_audit(drift_programs):
    """fp8's declaration (same wire bytes as int8: one byte/element +
    fp32 block scales) against the audited u8 program — same calibrated
    band as the int8 legs, so a codec whose wire silently widens (the
    f16 upcast a raw f8 collective lowers to) fails the drift guard."""
    p = drift_programs[("ddp", "fp8")]
    declared = sum(p["declared"].values())
    audited = total_wire_bytes(p["text"], axis_size=8)
    assert 0.7 <= audited / declared <= 2.0, (audited, declared)
    # the wire rides 1-byte u8, never f16
    from ray_lightning_tpu.comm.audit import collective_wire_bytes
    wire = collective_wire_bytes(p["text"], axis_size=8)
    assert any(dt == "u8" for _op, dt in wire), wire
    assert not any(dt == "f16" for _op, dt in wire), wire
