"""Remat lever (core/remat.py + the ``configure_remat()`` hooks on
GPT / PipelinedGPT / BERT): policy mapping, probe physics, in-place
apply, and the invariant that makes the whole axis safe to sweep —
remat changes scheduling, never math, so every policy trains to the
same params."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.core import remat as rm
from ray_lightning_tpu.core.steps import build_init_fn, build_train_step
from ray_lightning_tpu.models.bert import BertMLMModule
from ray_lightning_tpu.models.gpt import CONFIGS, GPTLightningModule
from ray_lightning_tpu.models.pipeline_gpt import PipelinedGPT

BATCH = 8


def _example_batch(module):
    return jax.tree_util.tree_map(
        np.asarray, next(iter(module.train_dataloader())))


def _trained_params(module, steps=2):
    """Single-device train loop through the real step builder — the
    lightest full-fidelity path (forward + backward + optimizer)."""
    module.setup_model()
    batch = _example_batch(module)
    tx = module.configure_optimizers()
    if isinstance(tx, dict):
        tx = tx["optimizer"]
    state = jax.jit(build_init_fn(module, tx))(jax.random.PRNGKey(0),
                                               batch)
    step = jax.jit(build_train_step(module, tx))
    for _ in range(steps):
        state, _metrics = step(state, batch)
    return state.params


def assert_params_equal(a, b, atol=2e-3):
    """Policies must train to the same params up to bf16 fusion
    reassociation: recompute changes which ops fuse, bf16 accumulation
    order inside the regrouped fusions wiggles low bits, and the
    bf16-RESIDENT params (RLT_BF16_PARAMS default) then round a
    one-ULP flip on a handful of elements (measured 2/12288 at one
    ulp ≈ 9.8e-4 after 2 tiny-GPT steps on this toolchain) — never
    the math itself."""
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x, np.float32),
                                   np.asarray(y, np.float32),
                                   rtol=1e-3, atol=atol)


# -- policy mapping --------------------------------------------------------

def test_policy_object_mapping_and_errors():
    for name in rm.POLICY_LADDER + rm.MOE_POLICIES:
        rm.policy_object(name)   # resolves
    assert rm.policy_object("full") is None    # jax default: save nothing
    with pytest.raises(ValueError, match="remat_policy"):
        rm.policy_object("warp")


def test_gpt_remat_policy_env_override(monkeypatch):
    """models/gpt.py _remat_policy keeps the RLT_REMAT_POLICY
    per-build override on top of the shared mapping."""
    from ray_lightning_tpu.models.gpt import _remat_policy

    assert _remat_policy("full") is None
    monkeypatch.setenv("RLT_REMAT_POLICY", "off")
    assert _remat_policy("full") is jax.checkpoint_policies\
        .everything_saveable


# -- probe physics ---------------------------------------------------------

def test_gpt_probe_ordering():
    """More aggressive saving -> more saved bytes; more aggressive
    recompute -> more backward matmul FLOPs.  ``full`` saves nothing
    and recomputes every dot; ``dots`` saves every dot output and
    recomputes none."""
    module = GPTLightningModule("tiny", dataset_size=4 * BATCH,
                                batch_size=BATCH)
    spec = module.configure_remat()
    batch = _example_batch(module)
    probes = {p: spec.probe(p, batch) for p in spec.policies}
    assert probes["off"].saved_bytes > probes["dots"].saved_bytes \
        > probes["full"].saved_bytes == 0
    assert probes["full"].recompute_flops > 0
    assert probes["dots"].recompute_flops == 0
    assert probes["off"].recompute_flops == 0
    assert probes["dots_no_batch"].recompute_flops > 0
    for p in probes.values():
        assert p.n_blocks == module.config.n_layer
        assert p.batch == BATCH
    # probes scale ~linearly in batch (the rescale contract
    # plan/cost.py remat_terms relies on; a few batch-free residuals —
    # layernorm stats over [T, C] etc. — keep it from being exact)
    module2 = GPTLightningModule("tiny", dataset_size=8 * BATCH,
                                 batch_size=2 * BATCH)
    double = module2.configure_remat().probe("off", _example_batch(module2))
    assert 1.9 <= double.saved_bytes / probes["off"].saved_bytes <= 2.0


def test_apply_is_in_place_and_clone_safe():
    """apply() reconfigures THE module it was created from (resets the
    materialized model); a copy.copy clone's own spec leaves the
    original untouched — the planner's per-candidate isolation."""
    import copy

    module = GPTLightningModule("gpt2-medium")
    spec = module.configure_remat()
    assert spec.default == "dots"
    module.setup_model()
    spec.apply("full")
    assert module.config.remat and module.config.remat_policy == "full"
    assert module.model is None          # next setup_model rebuilds
    spec.apply("off")
    assert module.config.remat is False
    clone = copy.copy(module)
    clone.configure_remat().apply("dots_no_batch")
    assert clone.config.remat_policy == "dots_no_batch"
    assert module.config.remat is False  # original untouched
    with pytest.raises(ValueError, match="ladder"):
        spec.apply("warp")
    # MoE configs extend the ladder with the checkpoint_name save lists
    moe_spec = GPTLightningModule("gpt2-moe-8e").configure_remat()
    assert "dots_moe" in moe_spec.policies
    # dense configs don't
    assert "dots_moe" not in spec.policies


def test_boring_model_declares_no_ladder():
    from ray_lightning_tpu.models.boring import BoringModel
    assert BoringModel().configure_remat() is None


# -- remat never changes math ----------------------------------------------

def test_gpt_policy_parity():
    """Every policy trains tiny-GPT to the same params: remat is a
    scheduling decision (what to save vs recompute), never a numerics
    one — the property that makes the planner free to sweep it."""
    reference = None
    for policy in ("off", "full", "dots"):
        module = GPTLightningModule("tiny", dataset_size=4 * BATCH,
                                    batch_size=BATCH)
        module.configure_remat().apply(policy)
        params = _trained_params(module)
        if reference is None:
            reference = params
        else:
            assert_params_equal(reference, params)


def test_pipeline_gpt_policy_lever_and_parity():
    """The MPMD/pipeline family has the full ladder now (was a
    boolean): policies apply to the scanned stage_fn, parity holds
    across them, and the configure_mpmd() stage program carries the
    checkpoint so MPMD stages can trade stash memory for recompute."""
    cfg = dataclasses.replace(CONFIGS["tiny"])
    reference = None
    for policy in ("off", "full", "dots"):
        module = PipelinedGPT(cfg, n_microbatches=2,
                              dataset_size=4 * BATCH, batch_size=BATCH)
        spec = module.configure_remat()
        assert spec.policies == rm.POLICY_LADDER
        spec.apply(policy)
        params = _trained_params(module)
        if reference is None:
            reference = params
        else:
            assert_params_equal(reference, params)
    # the MPMD stage_fn inherits the lever: a remat'd config's stage
    # program contains the checkpoint region, an off config's doesn't
    def stage_jaxpr(policy):
        module = PipelinedGPT(cfg, dataset_size=4 * BATCH,
                              batch_size=BATCH)
        module.configure_remat().apply(policy)
        mspec = module.configure_mpmd()
        h = jnp.zeros((2, cfg.block_size, cfg.n_embd), cfg.dtype)
        layer = jax.eval_shape(
            lambda k: module._block.init(k, h, True)["params"],
            jax.random.PRNGKey(0))
        return str(jax.make_jaxpr(mspec.stage_fn)(layer, h))
    assert "remat" in stage_jaxpr("dots")
    assert "remat" not in stage_jaxpr("off")


def test_bert_ladder_and_parity():
    """BERT gained the lever (BertConfig.remat/remat_policy were
    absent pre-PR-12): the spec covers the generic ladder, probes see
    the encoder layers, and parity holds across policies (the MLM
    mask rides the state PRNG, identical across runs)."""
    probe_module = BertMLMModule("tiny", batch_size=BATCH,
                                 train_size=4 * BATCH)
    spec = probe_module.configure_remat()
    assert spec.policies == rm.POLICY_LADDER and spec.default == "off"
    probes = {p: spec.probe(p, _example_batch(probe_module))
              for p in ("off", "dots", "full")}
    assert probes["off"].saved_bytes > probes["dots"].saved_bytes > 0
    assert probes["full"].recompute_flops > 0
    reference = None
    for policy in ("off", "dots"):
        module = BertMLMModule("tiny", batch_size=BATCH,
                               train_size=4 * BATCH)
        module.configure_remat().apply(policy)
        params = _trained_params(module)
        if reference is None:
            reference = params
        else:
            assert_params_equal(reference, params)
