"""Run every example script in --smoke-test mode, as the reference runs
its examples end-to-end in CI and under Ray Client (test_client*.py,
test.yaml:95-103).  Each runs in a subprocess so CLI parsing, imports and
env handling are exercised exactly as a user would hit them."""

import os
import subprocess
import sys

import pytest

EXAMPLES = [
    "ray_lightning_tpu.examples.ray_ddp_example",
    "ray_lightning_tpu.examples.ray_ddp_tune",
    "ray_lightning_tpu.examples.ray_ddp_sharded_example",
    "ray_lightning_tpu.examples.ray_spmd_example",
    "ray_lightning_tpu.examples.ray_longcontext_example",
    "ray_lightning_tpu.examples.ray_moe_example",
    "ray_lightning_tpu.examples.ray_pipeline_example",
    "ray_lightning_tpu.examples.ray_perf_tuning_example",
]


def run_example(module: str, *extra: str) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    # examples choose their own platform; clear the test-session forcing
    for k in ("XLA_FLAGS",):
        env.pop(k, None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run(
        [sys.executable, "-m", module, "--smoke-test", *extra],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.parametrize("module", EXAMPLES)
def test_example_smoke(module):
    proc = run_example(module)
    assert proc.returncode == 0, (
        f"{module} failed\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")


def test_ddp_example_tune_smoke():
    proc = run_example(EXAMPLES[0], "--tune")
    assert proc.returncode == 0, (
        f"tune sweep failed\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    assert "Best hyperparameters found were" in proc.stdout
