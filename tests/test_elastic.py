"""Elastic plane units: config resolution, fault specs, snapshot
cadence + backpressure, loader rescale, fleet-health metrics, and the
failure classifier (ray_lightning_tpu/elastic/).

The end-to-end legs live elsewhere: the 2-worker chaos run in
tests/test_failure.py, the N→M restore equality in
tests/test_sharded_checkpoint.py.
"""

import os

import numpy as np
import pytest

import jax

from ray_lightning_tpu import DataLoader, ElasticConfig, Trainer
from ray_lightning_tpu.elastic.driver import (_restartable,
                                              latest_snapshot_step)
from ray_lightning_tpu.elastic.faults import (FaultSpec, parse_fault)
from ray_lightning_tpu.models import BoringModel
from ray_lightning_tpu.models.boring import RandomDataset
from ray_lightning_tpu.telemetry.aggregator import (TelemetryAggregator,
                                                    WorkerHeartbeatTimeout)
from ray_lightning_tpu.telemetry.exporter import render_prometheus
from ray_lightning_tpu.utils.checkpoint import ShardedCheckpointer


# -- config ---------------------------------------------------------------

def test_elastic_config_resolve_env(monkeypatch):
    monkeypatch.setenv("RLT_ELASTIC", "1")
    monkeypatch.setenv("RLT_ELASTIC_EVERY", "25")
    monkeypatch.setenv("RLT_ELASTIC_DIR", "/tmp/snaps")
    monkeypatch.setenv("RLT_ELASTIC_MAX_RESTARTS", "5")
    monkeypatch.setenv("RLT_ELASTIC_MIN_WORKERS", "2")
    monkeypatch.setenv("RLT_ELASTIC_KEEP", "7")
    monkeypatch.setenv("RLT_ELASTIC_PRESERVE_BATCH", "0")
    cfg = ElasticConfig.resolve(None)
    assert cfg == ElasticConfig(
        enabled=True, snapshot_every_n_steps=25, snapshot_dir="/tmp/snaps",
        max_restarts=5, min_workers=2, preserve_global_batch=False,
        max_to_keep=7)
    # worker_env -> resolve round-trips (the RLT_COMM* contract)
    for k in list(os.environ):
        if k.startswith("RLT_ELASTIC"):
            monkeypatch.delenv(k)
    for k, v in cfg.worker_env().items():
        monkeypatch.setenv(k, v)
    assert ElasticConfig.resolve(None) == cfg


def test_elastic_config_forms():
    assert not ElasticConfig.resolve(None).enabled   # default off
    assert ElasticConfig.resolve(True).enabled
    cfg = ElasticConfig.resolve({"snapshot_every_n_steps": 4})
    assert cfg.enabled and cfg.snapshot_every_n_steps == 4
    with pytest.raises(ValueError):
        ElasticConfig(enabled=True, min_workers=0)
    with pytest.raises(TypeError):
        ElasticConfig.resolve(3.14)
    assert ElasticConfig().resolve_dir("/root/x") == "/root/x/elastic"


# -- fault specs ----------------------------------------------------------

def test_fault_spec_parsing():
    s = parse_fault("kill:rank=1,step=5")
    assert s == FaultSpec("kill", 1, 5)
    assert s.should_fire(1, 5) and s.should_fire(1, 9)
    assert not s.should_fire(0, 5) and not s.should_fire(1, 4)
    assert parse_fault("slow:rank=0,step=2,seconds=0.25").seconds == 0.25
    assert parse_fault("kill:rank=2,step=3,code=9").exit_code == 9
    assert parse_fault(s.describe()) == s
    for bad in ("", "kill", "boom:rank=1,step=2", "kill:step=2",
                "kill:rank=1,step=0", "kill:rank=1,step=2,what=3"):
        with pytest.raises(ValueError):
            parse_fault(bad)


def test_slow_fault_injects_stall(tmp_path, seed):
    """The slow-rank fault measurably stalls the run (the straggler
    harness) without changing its result."""
    import time
    t0 = time.monotonic()
    trainer = Trainer(
        max_epochs=1, max_steps=3, enable_checkpointing=False,
        num_sanity_val_steps=0, limit_val_batches=0, seed=0,
        log_every_n_steps=1, default_root_dir=str(tmp_path))
    os.environ["RLT_FAULT"] = "slow:rank=0,step=2,seconds=0.2"
    try:
        trainer.fit(BoringModel())
    finally:
        os.environ.pop("RLT_FAULT", None)
    assert trainer.global_step == 3
    assert time.monotonic() - t0 >= 0.4   # steps 2 and 3 each stalled


# -- snapshotting ---------------------------------------------------------

def test_snapshot_cadence_and_stats(tmp_path, seed):
    snap = str(tmp_path / "elastic")
    trainer = Trainer(
        max_epochs=10, max_steps=6, enable_checkpointing=False,
        num_sanity_val_steps=0, limit_val_batches=0, seed=0,
        log_every_n_steps=1, default_root_dir=str(tmp_path),
        elastic={"snapshot_every_n_steps": 2, "snapshot_dir": snap})
    trainer.fit(BoringModel())
    stats = trainer.elastic_stats()
    assert stats["snapshots"] + stats["skipped"] == 3   # steps 2, 4, 6
    assert stats["snapshots"] >= 1
    trainer.wait_for_checkpoints()
    ck = ShardedCheckpointer(snap)
    steps = ck.all_steps()
    ck.close()
    assert steps and all(s in (2, 4, 6) for s in steps)
    assert latest_snapshot_step(snap) == steps[-1]


def test_snapshot_backpressure_skips_never_queues(tmp_path, seed,
                                                 monkeypatch):
    """While a previous save is still writing, a single-process cadence
    hit is SKIPPED (bounded backpressure), not queued."""
    monkeypatch.setattr(ShardedCheckpointer, "saving_in_progress",
                        lambda self: True)
    snap = str(tmp_path / "elastic")
    trainer = Trainer(
        max_epochs=10, max_steps=4, enable_checkpointing=False,
        num_sanity_val_steps=0, limit_val_batches=0, seed=0,
        log_every_n_steps=1, default_root_dir=str(tmp_path),
        elastic={"snapshot_every_n_steps": 1, "snapshot_dir": snap})
    trainer.fit(BoringModel())
    stats = trainer.elastic_stats()
    assert stats["snapshots"] == 0
    assert stats["skipped"] == 4
    assert stats["stall_seconds"] == 0.0


def test_elastic_off_keeps_trainer_inert(tmp_path, seed):
    trainer = Trainer(
        max_epochs=1, max_steps=2, enable_checkpointing=False,
        num_sanity_val_steps=0, limit_val_batches=0, seed=0,
        log_every_n_steps=1, default_root_dir=str(tmp_path))
    trainer.fit(BoringModel())
    assert trainer._snapshotter is None
    assert trainer.elastic_stats() is None
    assert not (tmp_path / "elastic").exists()


# -- shrink plumbing ------------------------------------------------------

def test_elastic_rescale_preserves_global_batch(tmp_path):
    trainer = Trainer(
        max_epochs=1, enable_checkpointing=False, seed=0,
        default_root_dir=str(tmp_path),
        elastic={"snapshot_every_n_steps": 0})
    trainer._elastic_state = {"initial_workers": 4}
    trainer._world = {"world_size": 2, "global_rank": 0, "local_rank": 0,
                     "node_rank": 0}
    loader = DataLoader(RandomDataset(32, 64), batch_size=3)
    out = trainer._elastic_rescale_loader(loader, "train")
    assert out.batch_size == 6          # 4 workers x 3 == 2 workers x 6
    assert out.dataset is loader.dataset
    assert loader.batch_size == 3       # original untouched

    # non-dividing global batch: warn and keep the per-worker size
    trainer._world["world_size"] = 5
    same = trainer._elastic_rescale_loader(loader, "train")
    assert same.batch_size == 3

    # no shrink -> no-op (the common, attempt-1 case)
    trainer._world["world_size"] = 4
    assert trainer._elastic_rescale_loader(loader, "train") is loader


def test_failure_classifier():
    assert _restartable(RuntimeError("anything"), dead_ranks=[1])
    assert _restartable(WorkerHeartbeatTimeout("rank 1 silent"), [])
    assert _restartable(RuntimeError(
        "actor rlt-worker-1 died (connection lost)"), [])
    # a deterministic user exception must propagate, not retry
    assert not _restartable(RuntimeError("ValueError in training_step"),
                            [])


def test_latest_snapshot_step_missing_dir(tmp_path):
    assert latest_snapshot_step(str(tmp_path / "nope")) is None


# -- fleet health on /metrics (satellite: watchdog verdicts become
#    metrics) -------------------------------------------------------------

class _FakeHandle:
    def __init__(self, alive):
        self._alive = alive

    def alive(self):
        return self._alive


def test_worker_alive_gauges_and_restarts_counter(tmp_path):
    clock = [100.0]
    agg = TelemetryAggregator(str(tmp_path), heartbeat_timeout=5.0,
                              clock=lambda: clock[0])
    agg.register_worker(0, _FakeHandle(True))
    agg.register_worker(1, _FakeHandle(False))
    agg.set_restarts(2)
    agg.watchdog_check()
    assert agg.fleet_health() == {0: 1, 1: 0}

    latest = agg.latest_metrics()
    assert -1 in latest
    series = {(m["name"], m["labels"].get("worker")): m["value"]
              for m in latest[-1]["metrics"]}
    assert series[("rlt_worker_alive", "0")] == 1
    assert series[("rlt_worker_alive", "1")] == 0
    assert series[("rlt_restarts_total", None)] == 2

    text = render_prometheus(agg)
    assert 'rlt_worker_alive{rank="-1",worker="1"} 0' in text
    assert 'rlt_restarts_total{rank="-1"} 2' in text


def test_worker_alive_falls_back_to_heartbeat_age(tmp_path):
    """Backends whose probe cannot answer (alive() is None) derive the
    verdict from heartbeat age."""
    clock = [100.0]
    agg = TelemetryAggregator(str(tmp_path), heartbeat_timeout=5.0,
                              clock=lambda: clock[0])
    agg.register_worker(0, _FakeHandle(None))
    agg.register_worker(1, _FakeHandle(None))
    for rank, pid in ((0, 11), (1, 22)):
        agg.maybe_ingest({"__rlt_telemetry__": 1, "kind": "heartbeat",
                          "rank": rank, "pid": pid, "wall": 0.0})
    agg.watchdog_check()
    assert agg.fleet_health() == {0: 1, 1: 1}
    # rank 1 goes silent past the timeout; rank 0 keeps beating
    clock[0] = 110.0
    agg.maybe_ingest({"__rlt_telemetry__": 1, "kind": "heartbeat",
                      "rank": 0, "pid": 11, "wall": 0.0})
    agg.watchdog_check()
    assert agg.fleet_health() == {0: 1, 1: 0}


# -- reshard unit (the full-trainer N->M legs live in
#    tests/test_sharded_checkpoint.py) ------------------------------------

def test_reshard_rejects_incompatible_shapes(tmp_path, seed):
    """A genuinely different model must raise naming the leaf, not
    silently restore the saved shape (orbax would)."""
    t1 = Trainer(max_epochs=10, max_steps=1, enable_checkpointing=False,
                 num_sanity_val_steps=0, limit_val_batches=0, seed=0,
                 log_every_n_steps=1, default_root_dir=str(tmp_path))
    t1.fit(BoringModel())
    ck = str(tmp_path / "ck")
    t1.save_sharded_checkpoint(ck)
    t1.wait_for_checkpoints()

    class WiderBoring(BoringModel):
        def configure_model(self):
            from ray_lightning_tpu.models.boring import _Linear
            return _Linear(5)    # 2 -> 5 output features

    t2 = Trainer(max_epochs=10, max_steps=1, enable_checkpointing=False,
                 num_sanity_val_steps=0, limit_val_batches=0, seed=0,
                 log_every_n_steps=1, default_root_dir=str(tmp_path / "b"),
                 resume_from_checkpoint=ck)
    with pytest.raises(Exception, match="kernel"):
        t2.fit(WiderBoring())


def test_rebucket_preserves_injected_error_sum():
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ray_lightning_tpu.elastic.reshard import _rebucket

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("data",))
    rep = {"w": NamedSharding(mesh, P())}
    old = {"w": np.arange(24, dtype=np.float32).reshape(4, 6)}
    for m in (1, 2, 8):
        new = np.asarray(_rebucket(old, m, rep)["w"])
        assert new.shape == (m, 6)
        np.testing.assert_allclose(new.sum(0) / m, old["w"].sum(0) / 4,
                                   rtol=1e-6)
