"""Elastic plane units: config resolution, fault specs, snapshot
cadence + backpressure, loader rescale, fleet-health metrics, and the
failure classifier (ray_lightning_tpu/elastic/).

The end-to-end legs live elsewhere: the 2-worker chaos run in
tests/test_failure.py, the N→M restore equality in
tests/test_sharded_checkpoint.py.
"""

import os

import numpy as np
import pytest

import jax

from ray_lightning_tpu import DataLoader, ElasticConfig, Trainer
from ray_lightning_tpu.elastic.driver import (_restartable,
                                              latest_snapshot_step)
from ray_lightning_tpu.elastic.faults import (FaultSpec, parse_fault)
from ray_lightning_tpu.models import BoringModel
from ray_lightning_tpu.models.boring import RandomDataset
from ray_lightning_tpu.telemetry.aggregator import (TelemetryAggregator,
                                                    WorkerHeartbeatTimeout)
from ray_lightning_tpu.telemetry.exporter import render_prometheus
from ray_lightning_tpu.utils.checkpoint import ShardedCheckpointer


# -- config ---------------------------------------------------------------

def test_elastic_config_resolve_env(monkeypatch):
    monkeypatch.setenv("RLT_ELASTIC", "1")
    monkeypatch.setenv("RLT_ELASTIC_EVERY", "25")
    monkeypatch.setenv("RLT_ELASTIC_DIR", "/tmp/snaps")
    monkeypatch.setenv("RLT_ELASTIC_MAX_RESTARTS", "5")
    monkeypatch.setenv("RLT_ELASTIC_MIN_WORKERS", "2")
    monkeypatch.setenv("RLT_ELASTIC_KEEP", "7")
    monkeypatch.setenv("RLT_ELASTIC_PRESERVE_BATCH", "0")
    monkeypatch.setenv("RLT_ELASTIC_REDUNDANCY", "2")
    monkeypatch.setenv("RLT_ELASTIC_REDUNDANCY_EVERY", "4")
    monkeypatch.setenv("RLT_ELASTIC_SNAPSHOT_FAILURES", "9")
    cfg = ElasticConfig.resolve(None)
    assert cfg == ElasticConfig(
        enabled=True, snapshot_every_n_steps=25, snapshot_dir="/tmp/snaps",
        max_restarts=5, min_workers=2, preserve_global_batch=False,
        max_to_keep=7, redundancy=2, redundancy_every_n_steps=4,
        max_snapshot_failures=9)
    # worker_env -> resolve round-trips (the RLT_COMM* contract)
    for k in list(os.environ):
        if k.startswith("RLT_ELASTIC"):
            monkeypatch.delenv(k)
    for k, v in cfg.worker_env().items():
        monkeypatch.setenv(k, v)
    assert ElasticConfig.resolve(None) == cfg


def test_elastic_config_forms():
    assert not ElasticConfig.resolve(None).enabled   # default off
    assert ElasticConfig.resolve(True).enabled
    cfg = ElasticConfig.resolve({"snapshot_every_n_steps": 4})
    assert cfg.enabled and cfg.snapshot_every_n_steps == 4
    with pytest.raises(ValueError):
        ElasticConfig(enabled=True, min_workers=0)
    with pytest.raises(TypeError):
        ElasticConfig.resolve(3.14)
    assert ElasticConfig().resolve_dir("/root/x") == "/root/x/elastic"


# -- fault specs ----------------------------------------------------------

def test_fault_spec_parsing():
    s = parse_fault("kill:rank=1,step=5")
    assert s == FaultSpec("kill", 1, 5)
    assert s.should_fire(1, 5) and s.should_fire(1, 9)
    assert not s.should_fire(0, 5) and not s.should_fire(1, 4)
    assert parse_fault("slow:rank=0,step=2,seconds=0.25").seconds == 0.25
    assert parse_fault("kill:rank=2,step=3,code=9").exit_code == 9
    assert parse_fault(s.describe()) == s
    for bad in ("", "kill", "boom:rank=1,step=2", "kill:step=2",
                "kill:rank=1,step=0", "kill:rank=1,step=2,what=3"):
        with pytest.raises(ValueError):
            parse_fault(bad)


def test_slow_fault_injects_stall(tmp_path, seed):
    """The slow-rank fault measurably stalls the run (the straggler
    harness) without changing its result."""
    import time
    t0 = time.monotonic()
    trainer = Trainer(
        max_epochs=1, max_steps=3, enable_checkpointing=False,
        num_sanity_val_steps=0, limit_val_batches=0, seed=0,
        log_every_n_steps=1, default_root_dir=str(tmp_path))
    os.environ["RLT_FAULT"] = "slow:rank=0,step=2,seconds=0.2"
    try:
        trainer.fit(BoringModel())
    finally:
        os.environ.pop("RLT_FAULT", None)
    assert trainer.global_step == 3
    assert time.monotonic() - t0 >= 0.4   # steps 2 and 3 each stalled


def test_fault_list_and_new_kinds():
    """Tier-2 harness: semicolon lists, snapkill, peerdrop — and parse
    errors that name the bad clause."""
    from ray_lightning_tpu.elastic.faults import parse_faults

    specs = parse_faults("kill:rank=1,step=5 ; kill:rank=2,step=9")
    assert [(s.rank, s.step) for s in specs] == [(1, 5), (2, 9)]
    snap = parse_fault("snapkill:rank=1,step=4,code=7")
    assert snap.kind == "snapkill" and snap.exit_code == 7
    assert parse_fault(snap.describe()) == snap
    drop = parse_fault("peerdrop:rank=0,step=3,count=5")
    assert drop.count == 5
    assert parse_fault(drop.describe()) == drop
    with pytest.raises(ValueError, match="boom:rank=2,step=1"):
        parse_faults("kill:rank=1,step=5;boom:rank=2,step=1")
    with pytest.raises(ValueError, match="names no fault"):
        parse_faults(" ; ")
    with pytest.raises(ValueError):
        parse_fault("peerdrop:rank=0,step=1,count=0")


def test_peerdrop_swallows_inbound_frames():
    from ray_lightning_tpu.cluster import worker_state

    worker_state.reset_for_tests()
    try:
        worker_state.arm_peer_drop(2)
        box = worker_state.peer_mailbox()
        for i in range(3):
            worker_state.peer_push({"tag": ("t", i), "wire": i})
        # first two dropped, third delivered
        assert worker_state.peer_drop_pending() == 0
        assert box.take(("t", 2), 0.2) == 2
        with pytest.raises(Exception):
            box.take(("t", 0), 0.05)
    finally:
        worker_state.reset_for_tests()


# -- snapshotting ---------------------------------------------------------

def test_snapshot_cadence_and_stats(tmp_path, seed):
    snap = str(tmp_path / "elastic")
    trainer = Trainer(
        max_epochs=10, max_steps=6, enable_checkpointing=False,
        num_sanity_val_steps=0, limit_val_batches=0, seed=0,
        log_every_n_steps=1, default_root_dir=str(tmp_path),
        elastic={"snapshot_every_n_steps": 2, "snapshot_dir": snap})
    trainer.fit(BoringModel())
    stats = trainer.elastic_stats()
    assert stats["snapshots"] + stats["skipped"] == 3   # steps 2, 4, 6
    assert stats["snapshots"] >= 1
    trainer.wait_for_checkpoints()
    ck = ShardedCheckpointer(snap)
    steps = ck.all_steps()
    ck.close()
    assert steps and all(s in (2, 4, 6) for s in steps)
    assert latest_snapshot_step(snap) == steps[-1]


def test_snapshot_backpressure_skips_never_queues(tmp_path, seed,
                                                 monkeypatch):
    """While a previous save is still writing, a single-process cadence
    hit is SKIPPED (bounded backpressure), not queued."""
    monkeypatch.setattr(ShardedCheckpointer, "saving_in_progress",
                        lambda self: True)
    snap = str(tmp_path / "elastic")
    trainer = Trainer(
        max_epochs=10, max_steps=4, enable_checkpointing=False,
        num_sanity_val_steps=0, limit_val_batches=0, seed=0,
        log_every_n_steps=1, default_root_dir=str(tmp_path),
        elastic={"snapshot_every_n_steps": 1, "snapshot_dir": snap})
    trainer.fit(BoringModel())
    stats = trainer.elastic_stats()
    assert stats["snapshots"] == 0
    assert stats["skipped"] == 4
    assert stats["stall_seconds"] == 0.0


def test_elastic_off_keeps_trainer_inert(tmp_path, seed):
    trainer = Trainer(
        max_epochs=1, max_steps=2, enable_checkpointing=False,
        num_sanity_val_steps=0, limit_val_batches=0, seed=0,
        log_every_n_steps=1, default_root_dir=str(tmp_path))
    trainer.fit(BoringModel())
    assert trainer._snapshotter is None
    assert trainer.elastic_stats() is None
    assert not (tmp_path / "elastic").exists()


# -- shrink plumbing ------------------------------------------------------

def test_elastic_rescale_preserves_global_batch(tmp_path):
    trainer = Trainer(
        max_epochs=1, enable_checkpointing=False, seed=0,
        default_root_dir=str(tmp_path),
        elastic={"snapshot_every_n_steps": 0})
    trainer._elastic_state = {"initial_workers": 4}
    trainer._world = {"world_size": 2, "global_rank": 0, "local_rank": 0,
                     "node_rank": 0}
    loader = DataLoader(RandomDataset(32, 64), batch_size=3)
    out = trainer._elastic_rescale_loader(loader, "train")
    assert out.batch_size == 6          # 4 workers x 3 == 2 workers x 6
    assert out.dataset is loader.dataset
    assert loader.batch_size == 3       # original untouched

    # non-dividing global batch: warn and keep the per-worker size
    trainer._world["world_size"] = 5
    same = trainer._elastic_rescale_loader(loader, "train")
    assert same.batch_size == 3

    # no shrink -> no-op (the common, attempt-1 case)
    trainer._world["world_size"] = 4
    assert trainer._elastic_rescale_loader(loader, "train") is loader


def test_failure_classifier():
    assert _restartable(RuntimeError("anything"), dead_ranks=[1])
    assert _restartable(WorkerHeartbeatTimeout("rank 1 silent"), [])
    assert _restartable(RuntimeError(
        "actor rlt-worker-1 died (connection lost)"), [])
    # a deterministic user exception must propagate, not retry
    assert not _restartable(RuntimeError("ValueError in training_step"),
                            [])


def test_latest_snapshot_step_missing_dir(tmp_path):
    assert latest_snapshot_step(str(tmp_path / "nope")) is None


# -- fleet health on /metrics (satellite: watchdog verdicts become
#    metrics) -------------------------------------------------------------

class _FakeHandle:
    def __init__(self, alive):
        self._alive = alive

    def alive(self):
        return self._alive


def test_worker_alive_gauges_and_restarts_counter(tmp_path):
    clock = [100.0]
    agg = TelemetryAggregator(str(tmp_path), heartbeat_timeout=5.0,
                              clock=lambda: clock[0])
    agg.register_worker(0, _FakeHandle(True))
    agg.register_worker(1, _FakeHandle(False))
    agg.set_restarts(2)
    agg.watchdog_check()
    assert agg.fleet_health() == {0: 1, 1: 0}

    latest = agg.latest_metrics()
    assert -1 in latest
    series = {(m["name"], m["labels"].get("worker")): m["value"]
              for m in latest[-1]["metrics"]}
    assert series[("rlt_worker_alive", "0")] == 1
    assert series[("rlt_worker_alive", "1")] == 0
    assert series[("rlt_restarts_total", None)] == 2

    text = render_prometheus(agg)
    assert 'rlt_worker_alive{rank="-1",worker="1"} 0' in text
    assert 'rlt_restarts_total{rank="-1"} 2' in text


def test_worker_alive_falls_back_to_heartbeat_age(tmp_path):
    """Backends whose probe cannot answer (alive() is None) derive the
    verdict from heartbeat age."""
    clock = [100.0]
    agg = TelemetryAggregator(str(tmp_path), heartbeat_timeout=5.0,
                              clock=lambda: clock[0])
    agg.register_worker(0, _FakeHandle(None))
    agg.register_worker(1, _FakeHandle(None))
    for rank, pid in ((0, 11), (1, 22)):
        agg.maybe_ingest({"__rlt_telemetry__": 1, "kind": "heartbeat",
                          "rank": rank, "pid": pid, "wall": 0.0})
    agg.watchdog_check()
    assert agg.fleet_health() == {0: 1, 1: 1}
    # rank 1 goes silent past the timeout; rank 0 keeps beating
    clock[0] = 110.0
    agg.maybe_ingest({"__rlt_telemetry__": 1, "kind": "heartbeat",
                      "rank": 0, "pid": 11, "wall": 0.0})
    agg.watchdog_check()
    assert agg.fleet_health() == {0: 1, 1: 0}


# -- reshard unit (the full-trainer N->M legs live in
#    tests/test_sharded_checkpoint.py) ------------------------------------

def test_reshard_rejects_incompatible_shapes(tmp_path, seed):
    """A genuinely different model must raise naming the leaf, not
    silently restore the saved shape (orbax would)."""
    t1 = Trainer(max_epochs=10, max_steps=1, enable_checkpointing=False,
                 num_sanity_val_steps=0, limit_val_batches=0, seed=0,
                 log_every_n_steps=1, default_root_dir=str(tmp_path))
    t1.fit(BoringModel())
    ck = str(tmp_path / "ck")
    t1.save_sharded_checkpoint(ck)
    t1.wait_for_checkpoints()

    class WiderBoring(BoringModel):
        def configure_model(self):
            from ray_lightning_tpu.models.boring import _Linear
            return _Linear(5)    # 2 -> 5 output features

    t2 = Trainer(max_epochs=10, max_steps=1, enable_checkpointing=False,
                 num_sanity_val_steps=0, limit_val_batches=0, seed=0,
                 log_every_n_steps=1, default_root_dir=str(tmp_path / "b"),
                 resume_from_checkpoint=ck)
    with pytest.raises(Exception, match="kernel"):
        t2.fit(WiderBoring())


# -- async-snapshot failure hardening -------------------------------------

def test_snapshot_failure_is_absorbed_and_counted(tmp_path, seed,
                                                  monkeypatch):
    """A flaky async save must not kill training: caught, counted,
    retried next tick — and a later success resets the consecutive
    counter."""
    from ray_lightning_tpu.core.trainer import Trainer as _T

    calls = {"n": 0}
    real = _T.save_sharded_checkpoint

    def flaky(self, directory, step=None, max_to_keep=None):
        calls["n"] += 1
        if calls["n"] <= 2:
            raise OSError("disk full (injected)")
        return real(self, directory, step=step, max_to_keep=max_to_keep)

    monkeypatch.setattr(_T, "save_sharded_checkpoint", flaky)
    snap = str(tmp_path / "elastic")
    trainer = Trainer(
        max_epochs=10, max_steps=4, enable_checkpointing=False,
        num_sanity_val_steps=0, limit_val_batches=0, seed=0,
        log_every_n_steps=1, default_root_dir=str(tmp_path),
        elastic={"snapshot_every_n_steps": 1, "snapshot_dir": snap,
                 "max_snapshot_failures": 3})
    trainer.fit(BoringModel())
    assert trainer.global_step == 4            # training survived
    stats = trainer.elastic_stats()
    assert stats["failed"] == 2
    # steps 3/4: saved, or skipped behind step 3's still-writing save
    # (bounded backpressure) — either way the failure streak reset
    assert stats["snapshots"] >= 1
    assert stats["snapshots"] + stats["skipped"] == 2


def test_snapshot_consecutive_failures_eventually_raise(tmp_path, seed,
                                                        monkeypatch):
    """A permanently broken snapshot target must not fail silently."""
    from ray_lightning_tpu.core.trainer import Trainer as _T

    monkeypatch.setattr(
        _T, "save_sharded_checkpoint",
        lambda self, directory, step=None, max_to_keep=None:
        (_ for _ in ()).throw(OSError("target gone (injected)")))
    trainer = Trainer(
        max_epochs=10, max_steps=8, enable_checkpointing=False,
        num_sanity_val_steps=0, limit_val_batches=0, seed=0,
        log_every_n_steps=1, default_root_dir=str(tmp_path),
        elastic={"snapshot_every_n_steps": 1,
                 "snapshot_dir": str(tmp_path / "elastic"),
                 "max_snapshot_failures": 2})
    with pytest.raises(OSError, match="target gone"):
        trainer.fit(BoringModel())
    assert trainer.elastic_stats()["failed"] == 2


# -- parity redundancy (elastic/redundancy.py) ----------------------------

def test_parity_xor_roundtrip_every_position():
    from ray_lightning_tpu.elastic.redundancy import (ParityGroup,
                                                      recover_block,
                                                      xor_blocks)
    rng = np.random.default_rng(3)
    for world, k in ((2, 1), (3, 1), (4, 2)):
        blobs = [rng.bytes(50 + 11 * r) for r in range(world)]
        for dead in range(world):
            holder = ParityGroup.holder_of(dead, world, k)
            g = ParityGroup(holder, world, k)
            assert dead in g.covers
            parity = xor_blocks([blobs[m] for m in g.covers])
            others = [blobs[m] for m in g.covers if m != dead]
            assert recover_block(parity, others,
                                 len(blobs[dead])) == blobs[dead]


def test_pack_partition_splits_unique_and_replicated():
    """Sharded leaves (the ZeRO-1 optimizer partition) land in the
    unique blob with their global indices; replicated leaves in the
    replicated blob — and both assemble back bit-exact."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ray_lightning_tpu.elastic.redundancy import (assemble_leaf,
                                                      pack_partition,
                                                      unpack_partition)

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("data",))
    opt = jax.device_put(np.arange(8, dtype=np.float32).reshape(4, 2),
                         NamedSharding(mesh, P("data")))
    par = jax.device_put(np.ones((3,), np.float32),
                         NamedSharding(mesh, P()))
    state = {"opt": opt, "params": par}
    uu = unpack_partition(pack_partition(state, unique=True))
    rr = unpack_partition(pack_partition(state, unique=False))
    assert set(uu) == {"opt"} and set(rr) == {"params"}
    np.testing.assert_array_equal(
        assemble_leaf(uu["opt"]),
        np.arange(8, dtype=np.float32).reshape(4, 2))
    np.testing.assert_array_equal(assemble_leaf(rr["params"]),
                                  np.ones((3,), np.float32))
    # a gap in the pieces must raise, not silently zero-fill
    broken = dict(uu["opt"])
    broken["pieces"] = uu["opt"]["pieces"][:1]
    with pytest.raises(ValueError, match="cover"):
        assemble_leaf(broken)


class _FakeTrainer:
    def __init__(self, step):
        self.global_step = step
        self.current_epoch = 0
        self.callbacks = []
        self.lightning_module = None
        self.state = None


def _fake_manager(rank, world, blobs, reps, boxes, escrows, every=1):
    import cloudpickle
    from ray_lightning_tpu.elastic.config import ElasticConfig
    from ray_lightning_tpu.elastic.redundancy import (
        LoopbackParityTransport, RedundancyManager)

    cfg = ElasticConfig(enabled=True, redundancy=1,
                        redundancy_every_n_steps=every)
    mgr = RedundancyManager(
        _FakeTrainer(step=2), cfg, rank, world,
        LoopbackParityTransport(boxes, rank),
        store=lambda e, _r=rank: escrows.__setitem__(_r, e))
    mgr._pack = lambda unique, _r=rank: cloudpickle.dumps(
        blobs[_r] if unique else reps[_r])
    return mgr


def test_redundancy_manager_tick_and_driver_reconstruction():
    """Two simulated ranks tick over a loopback channel; killing either
    one, the driver-side reconstruction rebuilds its partition
    bit-exact and assembles a full-coverage package."""
    import threading
    from ray_lightning_tpu.cluster.peer import Mailbox
    from ray_lightning_tpu.elastic.redundancy import (assemble_leaf,
                                                      build_recovery)

    full = np.arange(8, dtype=np.float32).reshape(4, 2)
    blobs = {
        0: {"opt": {"shape": (4, 2), "dtype": "float32",
                    "pieces": [(((0, 2), (0, 2)), full[:2])]}},
        1: {"opt": {"shape": (4, 2), "dtype": "float32",
                    "pieces": [(((2, 4), (0, 2)), full[2:])]}},
    }
    reps = {r: {"params": {"shape": (3,), "dtype": "float32",
                           "pieces": [(((0, 3),), np.ones(3, np.float32))]}}
            for r in range(2)}
    boxes = {0: Mailbox(), 1: Mailbox()}
    escrows: dict = {}
    mgrs = [_fake_manager(r, 2, blobs, reps, boxes, escrows)
            for r in range(2)]
    threads = [threading.Thread(target=m.maybe_tick) for m in mgrs]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert set(escrows) == {0, 1}
    assert all(e["step"] == 2 for e in escrows.values())
    assert all(m.stats["parity_ticks"] == 1 for m in mgrs)
    assert all(m.stats["parity_bytes"] > 0 for m in mgrs)

    for dead in (0, 1):
        surviving = {r: e for r, e in escrows.items() if r != dead}
        pkg, why = build_recovery(surviving, dead, world=2, k=1)
        assert pkg is not None, why
        assert pkg["step"] == 2 and pkg["dead_rank"] == dead
        got = assemble_leaf(pkg["leaves"]["opt"])
        np.testing.assert_array_equal(got, full)
        np.testing.assert_array_equal(
            assemble_leaf(pkg["leaves"]["params"]),
            np.ones(3, np.float32))

    # gaps fall back (None + a reason), never raise
    pkg, why = build_recovery({}, 1, world=2, k=1)
    assert pkg is None and "no escrow" in why
    stale = {0: dict(escrows[0], step=1)}
    pkg, why = build_recovery(stale, 1, world=2, k=1)
    assert pkg is not None   # single survivor: one common step trivially


def test_redundancy_tick_times_out_without_peer_and_skips():
    """A parity tick whose peer never sends must cost a skipped tick
    (previous escrow retained), not a wedge or a crash."""
    from ray_lightning_tpu.cluster.peer import Mailbox

    boxes = {0: Mailbox(), 1: Mailbox()}
    escrows: dict = {}
    full = np.zeros((2, 2), np.float32)
    blobs = {0: {"opt": {"shape": (2, 2), "dtype": "float32",
                         "pieces": [(((0, 2), (0, 2)), full)]}}}
    mgr = _fake_manager(0, 2, blobs, {0: {}}, boxes, escrows)
    mgr.transport.timeout_s = 0.1
    assert mgr.maybe_tick() is False
    assert mgr.stats["parity_skipped"] == 1
    assert 0 not in escrows


def test_declared_parity_bytes_counts_only_sharded_leaves():
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ray_lightning_tpu.elastic.redundancy import declared_parity_bytes

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("data",))
    abstract = {"m": jax.ShapeDtypeStruct((8, 2), np.float32),
                "c": jax.ShapeDtypeStruct((), np.int32)}
    shardings = {"m": NamedSharding(mesh, P("data")),
                 "c": NamedSharding(mesh, P())}
    # (8,2) fp32 = 64B global, 32B/shard; k=1 every=1 -> 32
    assert declared_parity_bytes(abstract, shardings, 1, 1) == 32
    assert declared_parity_bytes(abstract, shardings, 2, 4) == 16


def test_rebucket_preserves_injected_error_sum():
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ray_lightning_tpu.elastic.reshard import _rebucket

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("data",))
    rep = {"w": NamedSharding(mesh, P())}
    old = {"w": np.arange(24, dtype=np.float32).reshape(4, 6)}
    for m in (1, 2, 8):
        new = np.asarray(_rebucket(old, m, rep)["w"])
        assert new.shape == (m, 6)
        np.testing.assert_allclose(new.sum(0) / m, old["w"].sum(0) / 4,
                                   rtol=1e-6)
