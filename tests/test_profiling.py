"""Observability callbacks: throughput metrics land in callback_metrics;
profiler traces are written and never break training (SURVEY.md §5
tracing/profiling parity)."""

import os

from ray_lightning_tpu import (
    JaxProfilerCallback,
    ThroughputMonitor,
    Trainer,
)
from ray_lightning_tpu.models import BoringModel
from ray_lightning_tpu.models.gpt import GPTLightningModule


def test_throughput_monitor_logs_metrics(tmp_path, seed):
    trainer = Trainer(max_epochs=1, limit_train_batches=8,
                      limit_val_batches=0, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      default_root_dir=str(tmp_path),
                      callbacks=[ThroughputMonitor(window=4)])
    trainer.fit(BoringModel(dataset_length=64, batch_size=4))
    cbm = trainer.callback_metrics
    assert cbm["steps_per_sec"] > 0
    assert cbm["samples_per_sec"] > 0
    assert cbm["epoch_time_s"] > 0


def test_throughput_monitor_tokens_for_sequences(tmp_path, seed):
    trainer = Trainer(max_epochs=1, limit_train_batches=8,
                      limit_val_batches=0, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      default_root_dir=str(tmp_path),
                      callbacks=[ThroughputMonitor(window=4)])
    module = GPTLightningModule("tiny", dataset_size=64, batch_size=4)
    trainer.fit(module)
    cbm = trainer.callback_metrics
    # token batches are [B, T]: tokens/sec = samples/sec * T
    assert cbm["tokens_per_sec"] > cbm["samples_per_sec"]


def test_throughput_monitor_with_chunked_dispatch(tmp_path, seed):
    """steps_per_execution>1 advances global_step k at a time and fires
    batch_end once per chunk: the monitor must still measure (delta
    tracking — a modulo window check would never trigger when k does
    not divide the window) and count samples for EVERY step of the
    chunk, not just the callback's batch."""
    ratios = []

    class Capture(ThroughputMonitor):
        def on_train_batch_end(self, trainer, module, outputs, batch,
                               idx):
            super().on_train_batch_end(trainer, module, outputs, batch,
                                       idx)
            cbm = trainer.callback_metrics
            if "samples_per_sec" in cbm:
                ratios.append(cbm["samples_per_sec"]
                              / cbm["steps_per_sec"])

    trainer = Trainer(max_epochs=1, limit_train_batches=15,
                      limit_val_batches=0, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      default_root_dir=str(tmp_path),
                      steps_per_execution=5,
                      callbacks=[Capture(window=4)])
    trainer.fit(BoringModel(dataset_length=64, batch_size=4))
    assert trainer.callback_metrics["steps_per_sec"] > 0
    # samples/sec must equal batch_size x steps/sec — i.e. every step of
    # each 5-step chunk was counted, not just the last one
    assert ratios and all(abs(r - 4.0) < 1e-6 for r in ratios)


def test_profiler_callback_writes_trace(tmp_path, seed):
    prof_dir = str(tmp_path / "prof")
    trainer = Trainer(max_epochs=1, limit_train_batches=6,
                      limit_val_batches=0, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      default_root_dir=str(tmp_path),
                      callbacks=[JaxProfilerCallback(
                          start_step=2, num_steps=2, log_dir=prof_dir)])
    trainer.fit(BoringModel(dataset_length=64, batch_size=4))
    # jax writes plugins/profile/<run>/ under the log dir
    found = []
    for root, _dirs, files in os.walk(prof_dir):
        found.extend(files)
    assert found, "no profiler trace files written"


def test_profiler_stops_cleanly_when_window_spans_train_end(tmp_path, seed):
    """Window past the end of training: on_train_end must stop the trace
    without raising."""
    trainer = Trainer(max_epochs=1, limit_train_batches=3,
                      limit_val_batches=0, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      default_root_dir=str(tmp_path),
                      callbacks=[JaxProfilerCallback(
                          start_step=2, num_steps=100)])
    trainer.fit(BoringModel(dataset_length=64, batch_size=4))
