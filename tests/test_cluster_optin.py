"""Opt-in real-hardware test, never run in CI.

Reference parity: test_ddp_gpu.py:125-136 gates a real-cluster run
behind ``CLUSTER=1`` (``ray.init("auto")``, workers sized to all
cluster GPUs).  Here ``CLUSTER=1`` runs one TPU-backed fit sized to the
attached chips — on a pod this exercises real ICI collectives; CI and
default local runs skip.

    CLUSTER=1 python -m pytest tests/test_cluster_optin.py -q
"""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("CLUSTER") != "1",
    reason="opt-in real-hardware test; set CLUSTER=1 to run")


def test_tpu_fit_on_attached_chips():
    import jax

    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.models.gpt import GPTLightningModule

    n = jax.device_count()
    module = GPTLightningModule("tiny", dataset_size=8 * n, batch_size=2 * n)
    trainer = Trainer(max_epochs=1, limit_val_batches=0,
                      num_sanity_val_steps=0, enable_checkpointing=False,
                      log_every_n_steps=1, seed=0,
                      strategy="ddp" if n > 1 else None)
    trainer.fit(module)
    assert trainer.global_step == 4
    assert np.isfinite(float(trainer.callback_metrics["loss"]))
