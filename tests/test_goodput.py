"""Goodput plane (telemetry/goodput.py): the full-run wall-clock
partition, measured MFU, and the ledger gates over them.

Three tiers:

- host-only units: the :class:`GoodputLedger` partition identity
  (``sum(buckets) == run_wall`` exact), overshoot scaling, the anatomy
  sub-split, replay re-attribution, fleet aggregation, the env-knob
  round-trip, and the benchmarks/ledger.py goodput bands (including the
  bootstrap path against a real pre-goodput ``BENCH_r*.json``);
- local-fit integration: the default ``flops_per_step`` jaxpr pricing
  against a hand-computed GPT matmul count (within 5%);
- distributed: the identity on a REAL 2-worker fit's per-rank and
  fleet docs, and the recovery-badput difference the elastic plane
  exists for — parity recovery shows ~0 ``replay`` seconds where the
  same fault with redundancy off shows a measured replay cost.
"""

import os
import sys
import time

import cloudpickle
import pytest

from ray_lightning_tpu import Callback, Trainer
from ray_lightning_tpu.models import BoringModel
from ray_lightning_tpu.telemetry.goodput import (
    FIT_BUCKETS,
    SERVE_BUCKETS,
    GoodputLedger,
    aggregate,
    check_identity,
    measured_mfu,
    reattribute_replay,
)

from tests.utils import cpu_plugin

# chaos fixtures run inside worker subprocesses which cannot import
# this test module by name; ship the classes by value (the
# test_failure.py seam)
cloudpickle.register_pickle_by_value(sys.modules[__name__])


# -- ledger units --------------------------------------------------------

def test_ledger_partition_identity_and_mfu():
    """Every fed second lands in exactly one bucket, the residual in
    ``other``, and the identity closes exactly against the wall."""
    t = [0.0]
    led = GoodputLedger("fit", device_tflops=1e-3, devices=2,
                        clock=lambda: t[0]).start()
    led.add("compile", 2.0)
    led.add("init", 0.5)
    for _ in range(10):
        led.note_step(0.3)
    led.add("data_wait", 0.2)
    led.set_flops_per_step(6e7)
    t[0] = 8.0
    doc = led.finalize()
    assert check_identity(doc)
    assert set(doc["buckets"]) == set(FIT_BUCKETS)
    assert doc["buckets"]["step"] == pytest.approx(3.0)
    assert doc["buckets"]["other"] == pytest.approx(2.3)
    assert doc["steps"] == 10
    assert doc["step_wall_mean_s"] == pytest.approx(0.3)
    assert doc["goodput_fraction"] == pytest.approx(3.0 / 8.0)
    # 6e7 FLOP / 0.3 s / (2 devices x 1e-3 TFLOPs peak) = 0.1
    assert doc["mfu"] == pytest.approx(0.1)
    assert measured_mfu(None, 0.3, 1e-3) is None   # never fabricated


def test_ledger_overshoot_scales_partition_closed():
    """Instrumented seconds exceeding the measured wall (overlapping
    accumulators) scale down proportionally — the identity still
    closes, nothing goes negative."""
    led = GoodputLedger("serve")
    led.note_step(4.0)          # decode
    led.add("prefill", 2.0)
    doc = led.finalize(3.0)
    assert check_identity(doc)
    assert doc["buckets"]["decode"] == pytest.approx(2.0)
    assert doc["buckets"]["prefill"] == pytest.approx(1.0)
    assert doc["goodput_fraction"] == pytest.approx(2.0 / 3.0)


def test_ledger_rejects_foreign_buckets_and_kinds():
    with pytest.raises(ValueError):
        GoodputLedger("train")
    led = GoodputLedger("fit")
    with pytest.raises(KeyError):
        led.add("decode", 1.0)          # serve bucket on a fit ledger
    assert "replay" not in SERVE_BUCKETS and "decode" not in FIT_BUCKETS


def test_useful_split_rides_anatomy_outside_identity():
    """An anatomy window sub-splits the useful bucket (compute /
    exposed / host / bubble) without entering the top-level identity."""
    led = GoodputLedger("fit")
    for _ in range(4):
        led.note_step(0.5)
    led.set_anatomy({"wall_s": 1.0, "compute_s": 0.6, "exposed_s": 0.3,
                     "host_s": 0.1, "bubble_fraction": 0.25})
    doc = led.finalize(4.0)
    assert check_identity(doc)
    split = doc["useful_split"]
    assert split["source"] == "anatomy"
    useful = doc["buckets"]["step"]
    assert split["bubble_s"] == pytest.approx(useful * 0.25)
    assert split["exposed_comm_s"] == pytest.approx(useful * 0.3)
    # bubble is carved out of compute, and the sub-split re-describes
    # ONE bucket: its parts never count toward the wall identity
    assert split["compute_s"] == pytest.approx(useful * 0.6 - useful * 0.25)
    assert sum(doc["buckets"].values()) == pytest.approx(4.0)


def test_reattribute_replay_is_identity_preserving():
    led = GoodputLedger("fit")
    for _ in range(10):
        led.note_step(0.5)
    doc = led.finalize(6.0)
    out = reattribute_replay(doc, 4)
    assert check_identity(out)
    assert out["run_wall_s"] == doc["run_wall_s"]
    assert out["buckets"]["replay"] == pytest.approx(2.0)
    assert out["buckets"]["step"] == pytest.approx(3.0)
    assert out["replayed_steps"] == 4
    assert out["goodput_fraction"] < doc["goodput_fraction"]
    # clamp: cannot move more than the step bucket holds
    clamped = reattribute_replay(doc, 100)
    assert check_identity(clamped)
    assert clamped["buckets"]["step"] >= 0
    # no-op path
    assert reattribute_replay(doc, 0)["buckets"].get("replay", 0.0) == 0.0


def test_aggregate_sums_ranks_and_extra_buckets_extend_wall():
    docs = []
    for _ in range(2):
        led = GoodputLedger("fit", device_tflops=1.0, devices=1)
        led.add("compile", 1.0)
        for _ in range(5):
            led.note_step(0.4)
        led.set_flops_per_step(1e9)
        docs.append(led.finalize(4.0))
    fleet = aggregate(docs, extra_buckets={"recovery": 1.5})
    assert check_identity(fleet)
    assert fleet["ranks"] == 2 and fleet["steps"] == 10
    # extra buckets extend BOTH the wall and their bucket
    assert fleet["run_wall_s"] == pytest.approx(9.5)
    assert fleet["buckets"]["recovery"] == pytest.approx(1.5)
    assert fleet["buckets"]["step"] == pytest.approx(4.0)
    assert fleet["mfu"] == pytest.approx(1e9 / 0.4 / 1e12, rel=1e-6)
    assert aggregate([]) == {}


def test_goodput_env_knobs_roundtrip_worker_env(monkeypatch):
    """RLT_GOODPUT* resolved on the driver ship through worker_env()
    and resolve identically on a worker (satellite: env round-trip)."""
    from ray_lightning_tpu.telemetry import TelemetryConfig
    from ray_lightning_tpu.telemetry import goodput as goodput_mod
    monkeypatch.delenv(goodput_mod.GOODPUT_ENV, raising=False)
    monkeypatch.delenv(goodput_mod.GOODPUT_TFLOPS_ENV, raising=False)
    # defaults: armed, no tflops -> nothing shipped (workers inherit
    # the same defaults)
    assert TelemetryConfig().worker_env() == {}
    assert TelemetryConfig().resolved_goodput() is True
    env = TelemetryConfig(goodput=False, goodput_tflops=275.0).worker_env()
    assert env[goodput_mod.GOODPUT_ENV] == "0"
    assert env[goodput_mod.GOODPUT_TFLOPS_ENV] == "275.0"
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    # the worker side sees only the env, no explicit config
    cfg = TelemetryConfig()
    assert cfg.resolved_goodput() is False
    assert cfg.resolved_goodput_tflops() == 275.0


# -- benchmarks/ledger.py goodput bands ----------------------------------

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _round(value=10.0, goodput=None, extra=None):
    rec = {"metric": "gpt_tiny_steps_per_sec", "unit": "steps/sec",
           "value": value}
    if goodput is not None:
        rec["goodput"] = goodput
    rec.update(extra or {})
    return [rec]


def test_ledger_bootstraps_against_pre_goodput_blob():
    """Comparing a goodput-bearing round against a REAL pre-goodput
    driver blob (BENCH_r05.json) must skip-with-note, never KeyError
    and never gate (satellite 1)."""
    from benchmarks import ledger
    prev_path = os.path.join(_REPO_ROOT, "BENCH_r05.json")
    prev_by = ledger.load_records(prev_path)
    assert prev_by and not any(
        isinstance(r.get("goodput"), dict) for r in prev_by.values()), \
        "fixture blob unexpectedly already carries goodput"
    # current round: same figures, plus the new goodput field
    curr = [dict(rec, goodput={"fraction": 0.8, "mfu": 0.35})
            for rec in prev_by.values()]
    report = ledger.compare(prev_path, curr)
    assert report["ok"], report["regressions"]
    notes = {(s["metric"], s["figure"]): s["note"]
             for s in report["skipped"]}
    assert notes, "one-sided goodput figures produced no skip notes"
    assert all("bootstrapping" in n for n in notes.values())
    assert any(fig == "goodput.fraction" for _, fig in notes)
    # and the reverse direction (figure dropped) notes too
    rev = ledger.compare(curr, prev_path)
    assert rev["ok"]
    assert any("missing from current round" in s["note"]
               for s in rev["skipped"])


def test_ledger_gates_injected_goodput_regression():
    from benchmarks import ledger
    prev = _round(goodput={"fraction": 0.80, "mfu": 0.40})
    # fraction 0.80 -> 0.60: -25% past the 10% band and past the 2-point
    # absolute floor
    bad = ledger.compare(prev, _round(goodput={"fraction": 0.60,
                                               "mfu": 0.40}))
    assert not bad["ok"]
    assert [r["figure"] for r in bad["regressions"]] == ["goodput.fraction"]
    # MFU gates independently
    bad_mfu = ledger.compare(prev, _round(goodput={"fraction": 0.80,
                                                   "mfu": 0.20}))
    assert not bad_mfu["ok"]
    assert [r["figure"] for r in bad_mfu["regressions"]] == ["goodput.mfu"]
    # same figures -> clean pass
    assert ledger.compare(prev, _round(goodput={"fraction": 0.80,
                                                "mfu": 0.40}))["ok"]


def test_ledger_goodput_floor_absorbs_small_drift():
    """A relatively large but absolutely tiny fraction drop stays under
    the MIN_GOODPUT_DELTA floor — wall-clock noise, not a regression."""
    from benchmarks import ledger
    prev = _round(goodput={"fraction": 0.010})
    curr = _round(goodput={"fraction": 0.008})      # -20% rel, 0.002 abs
    assert ledger.compare(prev, curr)["ok"]


def test_ledger_gates_measured_bubble_fraction():
    from benchmarks import ledger
    prev = _round(extra={"measured_bubble_fraction_1f1b": 0.10})
    worse = _round(extra={"measured_bubble_fraction_1f1b": 0.20})
    report = ledger.compare(prev, worse)
    assert not report["ok"]
    assert report["regressions"][0]["figure"] == \
        "measured_bubble_fraction_1f1b"
    # bootstrap: bubble figure new this round -> skipped, not gated
    boot = ledger.compare(_round(), worse)
    assert boot["ok"]
    assert any(s["figure"] == "measured_bubble_fraction_1f1b"
               for s in boot["skipped"])


# -- default flops_per_step pricing vs hand count ------------------------

@pytest.mark.slow
def test_default_flops_pricing_matches_hand_computed_gpt(tmp_path, seed):
    """The trainer's default MFU numerator — dot-counting the built
    train-step jaxpr — must land within 5% of the hand-computed matmul
    FLOPs of the GPT step (fwd + exact 2x backward, elementwise
    optimizer): the default pricing is exact for matmul-dominated
    models, not an estimate."""
    from ray_lightning_tpu.models.gpt import GPTConfig, GPTLightningModule

    B, T, C, V, L = 4, 32, 32, 512, 2
    cfg = GPTConfig(vocab_size=V, block_size=T, n_layer=L, n_head=2,
                    n_embd=C, remat=False, attention_impl="dot")
    module = GPTLightningModule(cfg, batch_size=B, dataset_size=8 * B)
    trainer = Trainer(max_epochs=1, limit_train_batches=2,
                      limit_val_batches=0, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      log_every_n_steps=1, default_root_dir=str(tmp_path),
                      telemetry=True)
    trainer.fit(module)
    doc = trainer._goodput_local
    assert doc is not None and check_identity(doc)
    flops = doc.get("flops_per_step")
    assert flops, "default jaxpr pricing produced no flops_per_step"
    # forward matmuls (2*M*N*K convention): per layer qkv 6BTC^2 +
    # scores/AV 2BT^2C each + proj 2BTC^2 + MLP 16BTC^2, plus the tied
    # vocab head 2BTCV; backward doubles every dot (dgrad + wgrad)
    fwd = L * (24 * B * T * C * C + 4 * B * T * T * C) + 2 * B * T * C * V
    expected = 3 * fwd
    assert abs(flops - expected) / expected < 0.05, (flops, expected)


# -- real 2-worker fit: the identity, fleetwide --------------------------

@pytest.mark.slow
def test_two_worker_fit_goodput_identity_fleetwide(tmp_path, seed):
    """The acceptance identity on a real distributed fit: every rank's
    doc closes exactly, the fleet aggregate closes exactly, and the
    export summary / trainer report carry the same partition."""
    trainer = Trainer(max_epochs=1, limit_train_batches=6,
                      limit_val_batches=0, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      log_every_n_steps=1, default_root_dir=str(tmp_path),
                      plugins=[cpu_plugin(2)],
                      telemetry={"heartbeat_interval": 0.5})
    trainer.fit(BoringModel())
    summary = trainer._telemetry_paths["summary"]
    assert "goodput" in summary, "no goodput section in export summary"
    gp = summary["goodput"]
    assert set(gp["per_rank"]) == {"0", "1"}
    for rank, doc in gp["per_rank"].items():
        assert doc["kind"] == "fit"
        assert check_identity(doc), (rank, doc)
        assert doc["steps"] == 6
        assert doc["buckets"]["step"] > 0
        assert doc["buckets"]["compile"] > 0
    fleet = gp["fleet"]
    assert check_identity(fleet), fleet
    assert fleet["ranks"] == 2 and fleet["steps"] == 12
    assert 0 < fleet["goodput_fraction"] <= 1
    # the driver-side report the bench harness exports is the fleet doc
    rep = trainer._goodput_report
    assert rep is not None and check_identity(rep)
    assert rep["goodput_fraction"] == fleet["goodput_fraction"]


# -- recovery badput: parity ~0 vs replay > 0 ----------------------------

class AdamBoring(BoringModel):
    """Adam moments make the ZeRO-1 shard a dead rank takes with it
    non-trivial (the test_failure.py fixture, shipped by value)."""

    def configure_optimizers(self):
        import optax
        return optax.adam(0.05)


class SlowStep(Callback):
    """Pace the steps so heartbeat-carried metrics briefs track the
    fleet's progress (the crash-step evidence the replayed-step
    attribution reads) and async snapshots commit between steps."""

    needs_batch = False

    def on_train_batch_end(self, trainer, module, outputs, batch, idx):
        time.sleep(0.05)


def _badput_trainer(tmp_path, snap, *, fault, elastic, max_steps=8):
    return Trainer(
        max_epochs=20, max_steps=max_steps, limit_val_batches=0,
        num_sanity_val_steps=0, enable_checkpointing=False, seed=0,
        log_every_n_steps=1, default_root_dir=str(tmp_path),
        callbacks=[SlowStep()],
        plugins=[cpu_plugin(2, strategy="zero1",
                            worker_env={"RLT_FAULT": fault})],
        telemetry={"heartbeat_interval": 0.2, "flush_every": 1,
                   "metrics_interval": 0.2},
        elastic=elastic)


@pytest.mark.slow
def test_parity_recovery_reports_zero_replay_badput(tmp_path, seed):
    """Parity recovery resumes AT the crash step — the goodput ledger
    must show zero ``replay`` seconds (the measured claim PR 13's
    zero-replay story reduces to)."""
    snap = str(tmp_path / "elastic")
    trainer = _badput_trainer(
        tmp_path, snap, fault="kill:rank=1,step=5",
        elastic={"snapshot_every_n_steps": 2, "snapshot_dir": snap,
                 "max_restarts": 2, "redundancy": 1})
    trainer.fit(AdamBoring(dataset_length=64, batch_size=2))
    rep = trainer._elastic_report
    assert rep["recovery"] == "parity" and rep["resumed_step"] == 5
    assert rep["replayed_steps"] == 0
    gp = trainer._goodput_report
    assert gp is not None and check_identity(gp)
    assert gp["buckets"]["replay"] == 0.0
    # the recovery decision itself is attributed, not hidden
    assert gp["buckets"]["recovery"] > 0


@pytest.mark.slow
def test_replay_recovery_measures_replayed_step_badput(tmp_path, seed):
    """The same fleet with redundancy off resumes from the last durable
    snapshot and re-executes steps — measured ``replay`` seconds > 0:
    parity vs replay is now a goodput difference, not a narrative."""
    snap = str(tmp_path / "elastic")
    trainer = _badput_trainer(
        tmp_path, snap, fault="kill:rank=1,step=9", max_steps=10,
        elastic={"snapshot_every_n_steps": 5, "snapshot_dir": snap,
                 "max_restarts": 2})
    trainer.fit(AdamBoring(dataset_length=64, batch_size=2))
    rep = trainer._elastic_report
    assert rep["recovery"] == "replay" and rep["resumed_step"] == 5
    # the fleet progressed well past step 5 before the kill at 9; the
    # last metrics brief pins the crash step several steps past the
    # resume point
    assert rep["replayed_steps"] >= 1
    gp = trainer._goodput_report
    assert gp is not None and check_identity(gp)
    assert gp["buckets"]["replay"] > 0
    assert gp["replayed_steps"] == rep["replayed_steps"]


# -- wire item / metrics mirror ------------------------------------------

def test_goodput_item_and_metrics_mirror():
    from ray_lightning_tpu.telemetry import goodput as goodput_mod
    from ray_lightning_tpu.telemetry.metrics import MetricsRegistry

    led = GoodputLedger("serve")
    led.note_step(1.0)
    doc = led.finalize(2.0)
    item = goodput_mod.goodput_item(3, doc)
    assert item["kind"] == "goodput" and item["rank"] == 3
    assert item["goodput"] is doc
    reg = MetricsRegistry()
    goodput_mod.publish_metrics(doc, registry=reg)
    assert reg.gauge("rlt_goodput_seconds").value(
        bucket="decode", kind="serve") == pytest.approx(1.0)
    assert reg.gauge("rlt_goodput_fraction").value(
        kind="serve") == pytest.approx(0.5)
