"""Pipeline parallelism (parallel/pipeline.py, models/pipeline_gpt.py).

Beyond reference parity (SURVEY.md §2.3: PP absent there).  The
load-bearing assertions are numerical: the GPipe schedule must produce
bit-comparable outputs AND gradients to plain sequential layer
execution — scheduling is an optimization, never semantics.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from ray_lightning_tpu.parallel.pipeline import (PipelineStrategy,
                                                 pipeline_forward)
from tests.conftest import assert_tree_allclose


def _toy_stack(n_layers, width, key):
    ks = jax.random.split(key, n_layers)
    return {
        "w": jax.vmap(
            lambda k: jax.random.normal(k, (width, width)) * 0.3)(ks),
        "b": jax.vmap(lambda k: jax.random.normal(k, (width,)) * 0.1)(ks),
    }


def _toy_stage_fn(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def _mesh(stage, data=1):
    devs = np.array(jax.devices()[:data * stage]).reshape(data, stage)
    return Mesh(devs, ("data", "stage"))


def _sequential(params, x):
    def body(h, p):
        return _toy_stage_fn(p, h), None
    return jax.lax.scan(body, x, params)[0]


@pytest.mark.parametrize("stages,microbatches", [(2, 2), (4, 4), (2, 4)])
def test_pipeline_matches_sequential(stages, microbatches):
    params = _toy_stack(8, 16, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))
    want = _sequential(params, x)
    got = pipeline_forward(_toy_stage_fn, params, x,
                           n_microbatches=microbatches,
                           mesh=_mesh(stages))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_gradients_match_sequential():
    params = _toy_stack(4, 8, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 8))
    mesh = _mesh(2, data=2)

    def loss_seq(p):
        return jnp.sum(_sequential(p, x) ** 2)

    def loss_pipe(p):
        out = pipeline_forward(_toy_stage_fn, p, x, n_microbatches=2,
                               mesh=mesh)
        return jnp.sum(out ** 2)

    g_seq = jax.grad(loss_seq)(params)
    g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    assert_tree_allclose(g_pipe, g_seq, rtol=5e-4, atol=5e-5)


def test_no_stage_axis_falls_back_to_scan():
    params = _toy_stack(4, 8, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8))
    got = pipeline_forward(_toy_stage_fn, params, x, mesh=None)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_sequential(params, x)),
                               rtol=1e-6)


def test_layers_must_divide_stages():
    params = _toy_stack(3, 8, jax.random.PRNGKey(0))
    x = jnp.zeros((4, 8))
    with pytest.raises(ValueError, match="divide"):
        pipeline_forward(_toy_stage_fn, params, x, mesh=_mesh(2))


def test_microbatches_must_divide_local_batch():
    params = _toy_stack(4, 8, jax.random.PRNGKey(0))
    x = jnp.zeros((8, 8))  # per-shard batch 4 with data=2
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_forward(_toy_stage_fn, params, x, n_microbatches=3,
                         mesh=_mesh(2, data=2))


def test_dropout_config_rejected():
    from ray_lightning_tpu.models.gpt import GPTConfig
    from ray_lightning_tpu.models.pipeline_gpt import PipelinedGPT
    with pytest.raises(ValueError, match="dropout"):
        PipelinedGPT(GPTConfig(vocab_size=64, block_size=16, n_layer=2,
                               n_head=2, n_embd=32, dropout=0.1))


def test_auto_attention_replaced_with_local():
    """Mesh-consulting attention impls would nest a shard_map inside the
    pipeline's manual region; the module must swap them out."""
    from ray_lightning_tpu.models.pipeline_gpt import PipelinedGPT
    assert PipelinedGPT("tiny").config.attention_impl == "local"


def test_remat_config_still_matches_sequential(seed):
    """cfg.remat wraps each layer in jax.checkpoint — gradients must be
    unchanged (remat is a memory trade, not math)."""
    from ray_lightning_tpu.models.gpt import GPTConfig
    from ray_lightning_tpu.models.pipeline_gpt import PipelinedGPT

    x = jnp.zeros((4, 16), jnp.int32)
    cfgs = [GPTConfig(vocab_size=64, block_size=16, n_layer=2, n_head=2,
                      n_embd=32, remat=r) for r in (False, True)]
    mods = [PipelinedGPT(c, n_microbatches=2) for c in cfgs]
    variables = mods[0].init_params(jax.random.PRNGKey(0), (x, x))

    def loss(mod, p):
        return jnp.sum(mod._forward(p, x).astype(jnp.float32) ** 2)

    g0 = jax.grad(functools.partial(loss, mods[0]))(variables["params"])
    g1 = jax.grad(functools.partial(loss, mods[1]))(variables["params"])
    assert_tree_allclose(g1, g0, rtol=1e-4, atol=1e-5)


def test_pipelined_gpt_trains_and_shards(seed):
    """End-to-end on a (data=2, stage=4) mesh: block params sharded on
    stage, loss finite and decreasing, val works."""
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.core.callbacks import Callback
    from ray_lightning_tpu.models.gpt import GPTConfig
    from ray_lightning_tpu.models.pipeline_gpt import PipelinedGPT

    cfg = GPTConfig(vocab_size=512, block_size=64, n_layer=4, n_head=2,
                    n_embd=64, remat=False)
    module = PipelinedGPT(cfg, n_microbatches=2, dataset_size=64,
                          batch_size=8, lr=1e-2)
    strategy = PipelineStrategy(stages=4)

    losses = []

    class Track(Callback):
        def on_train_batch_end(self, trainer, mod, metrics, batch, idx):
            losses.append(float(np.asarray(metrics["loss"])))

    trainer = Trainer(max_epochs=2, strategy=strategy,
                      enable_checkpointing=False, num_sanity_val_steps=0,
                      limit_val_batches=2, log_every_n_steps=1,
                      callbacks=[Track()], seed=0)
    trainer.fit(module)

    assert np.isfinite(losses).all()
    assert np.mean(losses[-4:]) < np.mean(losses[:4])
    spec = trainer.state.params["blocks"]["attn"]["qkv"]["kernel"].sharding.spec
    assert spec[0] == "stage", spec
    # optimizer moments follow the stage sharding (PP-natural ZeRO):
    # every non-scalar Adam leaf with a stacked layer dim is stage-sharded
    stage_sharded = [
        leaf for leaf in jax.tree_util.tree_leaves(trainer.state.opt_state)
        if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[:1] == (4,)
        and leaf.sharding.spec[:1] == ("stage",)]
    assert stage_sharded, "no stage-sharded optimizer moments found"
    assert "val_loss" in trainer.callback_metrics


def test_pipelined_gpt_predict(seed):
    """predict on the stage mesh returns dataset-order token ids."""
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.models.pipeline_gpt import PipelinedGPT

    module = PipelinedGPT("tiny", n_microbatches=2, dataset_size=16,
                          batch_size=8)
    trainer = Trainer(max_epochs=1, strategy=PipelineStrategy(stages=2),
                      enable_checkpointing=False, num_sanity_val_steps=0,
                      limit_val_batches=0, log_every_n_steps=1, seed=0)
    trainer.fit(module)
    preds = trainer.predict(module)
    assert len(preds) == 2
    for p in preds:
        p = np.asarray(p)
        assert p.shape == (8, module.config.block_size)
        assert p.dtype.kind == "i"
        assert (p >= 0).all() and (p < module.config.vocab_size).all()


def test_pipelined_gpt_same_loss_as_unpipelined(seed):
    """One train step on (data=2, stage=2) must produce the same loss as
    the identical model on a data-only mesh (scheduling ≠ semantics)."""
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.models.pipeline_gpt import PipelinedGPT

    def run(strategy):
        module = PipelinedGPT("tiny", n_microbatches=2, dataset_size=16,
                              batch_size=8)
        trainer = Trainer(max_epochs=1, max_steps=2, strategy=strategy,
                          enable_checkpointing=False,
                          num_sanity_val_steps=0, limit_val_batches=0,
                          log_every_n_steps=1, seed=0)
        trainer.fit(module)
        return float(trainer.callback_metrics["loss"])

    pipelined = run(PipelineStrategy(stages=2))
    plain = run("ddp")
    assert pipelined == pytest.approx(plain, rel=2e-3)
