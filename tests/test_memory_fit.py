"""Memory-fit audit for BASELINE config #5 (gpt2-1p3b) on its target
meshes, BEFORE any pod exists (VERDICT r3 next #7).

Two layers of evidence, both computed on the 8-virtual-device CPU mesh:

- **backend-reported**: ``compile().memory_analysis()`` per-device
  argument bytes of the real train step — the authoritative sharded
  TrainState footprint (params + fp32 master + Adam moments at the
  documented precision recipe).  Asserted to match the analytic
  per-leaf shard byte account within 10%, so a precision regression
  (params silently fp32, master un-sharded, moments widened) fails
  here no matter which side drifted.
- **analytic transients**: grads (bf16 tree), the fp32 update deltas
  (gathered full-size per device — audited f32 in
  tests/test_collective_audit.py), remat-saved layer-boundary
  activations, and the chunked-CE logit slab.  CPU ``temp`` bytes are
  deliberately NOT used: the CPU lowering materializes full attention
  scores that the TPU flash kernels never allocate.

Budgets: v5e = 16 GB HBM/chip (the 8-chip mesh shapes in
benchmarks/README.md), v4 = 32 GB/chip (BASELINE.md config #5's v4-128,
64 chips).  A 10% headroom is reserved for XLA workspace/fragmentation.
"""

from __future__ import annotations

import functools

import jax
import numpy as np
import pytest

from ray_lightning_tpu.core.steps import build_init_fn, build_train_step
from ray_lightning_tpu.models.gpt import (CONFIGS, GPTLightningModule,
                                          gpt_partition_rules)
from ray_lightning_tpu.parallel.strategy import (FullyShardedStrategy,
                                                 SpmdStrategy, Zero1Strategy)

GB = 1024 ** 3
V5E_HBM = 16 * GB
V4_HBM = 32 * GB
HEADROOM = 0.90          # fraction of HBM the accounted residents may use
GLOBAL_BATCH = 8

CFG = CONFIGS["gpt2-1p3b"]


def _abstract_state(module, tx, batch):
    return jax.eval_shape(build_init_fn(module, tx),
                          jax.random.PRNGKey(0), batch)


def _sharded_bytes(abstract, shardings, n_devices: int) -> int:
    """Per-device bytes of the state under the given shardings (exact:
    per-leaf shard shapes)."""
    total = 0
    for aval, sh in zip(jax.tree_util.tree_leaves(abstract),
                        jax.tree_util.tree_leaves(
                            shardings, is_leaf=lambda x: hasattr(x, "spec"))):
        shape = sh.shard_shape(aval.shape) if hasattr(sh, "shard_shape") \
            else aval.shape
        total += int(np.prod(shape, dtype=np.int64)) * aval.dtype.itemsize
    return total


def _n_params(abstract) -> int:
    return sum(int(np.prod(a.shape, dtype=np.int64))
               for a in jax.tree_util.tree_leaves(abstract.params))


def _transient_bytes(n_params: int, batch_local: int,
                     grads_sharded_by: int = 1,
                     updates_sharded_by: int = 1) -> int:
    """Analytic peak of the big per-device transients the state bytes
    miss (documented in the module docstring).  Grad and fp32-update
    trees mirror the PARAM sharding: replicated-param strategies
    (ddp/zero1) materialize them full-size per device (the audited f32
    all-gather of updates); param-sharded strategies keep both
    shard-sized."""
    cfg = CFG
    grads_bf16 = 2 * n_params // grads_sharded_by
    updates_f32 = 4 * n_params // updates_sharded_by
    acts = cfg.n_layer * batch_local * cfg.block_size * cfg.n_embd * 2
    block_peak = 12 * batch_local * cfg.block_size * cfg.n_embd * 2
    ce_chunk = (batch_local * (cfg.block_size // max(1, cfg.chunked_ce))
                * cfg.vocab_size * 4) * 2      # fwd + bwd slabs
    return grads_bf16 + updates_f32 + acts + block_peak + ce_chunk


def _shard_factors(name: str, n_dev: int) -> tuple:
    """(grads_sharded_by, updates_sharded_by) — conservative lower
    bounds on how the grad/update trees shard per strategy."""
    if name == "fsdp":
        return n_dev, n_dev
    if name == "spmd":
        # every large param is sharded by at least one size-2 axis
        # (tensor rules or the fsdp fallback); use the conservative min
        return 2, 2
    return 1, 1


STRATEGIES = {
    "zero1": lambda: Zero1Strategy(),
    "fsdp": lambda: FullyShardedStrategy(),
    # memory-first mesh for 1.3B on 8 chips: audited at fsdp=2,tensor=2
    # (data=2) the state alone is 7.35 GB/device and the total BREAKS
    # the v5e budget — fsdp=4 is the fitting layout this test pins
    "spmd": lambda: SpmdStrategy(rules=gpt_partition_rules(),
                                 axis_names=("data", "fsdp", "tensor"),
                                 axis_sizes={"fsdp": 4, "tensor": 2}),
}


@pytest.fixture(scope="module", params=sorted(STRATEGIES))
def audited(request):
    """Compile the REAL 1.3B train step under one strategy on the
    8-device mesh; yield every number the assertions need (compile is
    ~2 min per strategy).  The heavy body is memoized by strategy name:
    pytest's fixture-param regrouping re-instantiates module-scoped
    parametrized fixtures when single-param tests (plugin_path, the
    un-donated audits) interleave with the generic groups, and without
    the memo each re-instantiation re-pays the full compile (measured:
    4-7 compiles per run instead of 3)."""
    return _audited(request.param)


@functools.lru_cache(maxsize=None)
def _audited(name):
    strat = STRATEGIES[name]()
    module = GPTLightningModule("gpt2-1p3b", dataset_size=2 * GLOBAL_BATCH,
                                batch_size=GLOBAL_BATCH)
    module.setup_model()
    tx = module.configure_optimizers()
    mesh = strat.build_mesh(batch_hint=GLOBAL_BATCH)
    batch = jax.tree_util.tree_map(
        np.asarray, next(iter(module.train_dataloader())))
    abstract = _abstract_state(module, tx, batch)
    shardings = strat.state_shardings(mesh, abstract)
    jitted = jax.jit(build_train_step(module, tx), donate_argnums=0,
                     in_shardings=(shardings,
                                   strat.batch_shardings(mesh, batch)),
                     out_shardings=(shardings, None))
    comp = jitted.lower(abstract, batch).compile()
    n_dev = int(np.prod(list(mesh.shape.values())))
    mem = comp.memory_analysis()
    return {
        "name": name,
        "mesh": dict(mesh.shape),
        "n_dev": n_dev,
        "n_params": _n_params(abstract),
        "abstract": abstract,
        "compiled_args": mem.argument_size_in_bytes,
        "compiled_out": mem.output_size_in_bytes,
        "compiled_alias": mem.alias_size_in_bytes,
        "analytic_args": _sharded_bytes(abstract, shardings, n_dev),
        "batch_local": max(1, GLOBAL_BATCH // n_dev),
        "module": module,
        "batch": batch,
    }


def test_compiled_args_match_sharded_account(audited):
    """The compiled program's per-device argument bytes must match the
    per-leaf shard account within 10% — catches any precision or
    sharding regression on either side."""
    got, want = audited["compiled_args"], audited["analytic_args"]
    assert abs(got - want) <= 0.10 * want, (
        f"{audited['name']}: compiled args {got / GB:.2f} GB vs sharded "
        f"account {want / GB:.2f} GB")


def test_fits_v5e_8(audited):
    """Config #5's model class must fit the 8-chip v5e mesh shapes the
    benchmarks document (benchmarks/README.md) under every sharded
    strategy."""
    g_by, u_by = _shard_factors(audited["name"], audited["n_dev"])
    total = audited["compiled_args"] + _transient_bytes(
        audited["n_params"], audited["batch_local"],
        grads_sharded_by=g_by, updates_sharded_by=u_by)
    budget = HEADROOM * V5E_HBM
    assert total <= budget, (
        f"{audited['name']}: {total / GB:.2f} GB accounted vs "
        f"{budget / GB:.2f} GB budget on v5e-8 "
        f"(state {audited['compiled_args'] / GB:.2f})")


class _StubMesh:
    """Just enough mesh for the data-axis strategies' spec functions
    (they read only ``mesh.shape``), so per-device bytes at a target
    shard count can be accounted without 64 real devices."""

    def __init__(self, sizes: dict):
        self.shape = dict(sizes)
        self.axis_names = tuple(sizes)


def _state_bytes_at_dp(strat, abstract, dp: int) -> int:
    """Per-device state bytes under ``strat``'s own spec functions on a
    stub data=dp mesh (exact per-leaf shard shapes, divisibility
    honored the same way _axis_spec does)."""
    mesh = _StubMesh({"data": dp})

    def tree_bytes(tree, spec_fn):
        total = 0
        for path, aval in jax.tree_util.tree_flatten_with_path(tree)[0]:
            if getattr(aval, "ndim", 0) == 0:
                total += aval.dtype.itemsize
                continue
            pstr = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                            for p in path)
            spec = spec_fn(mesh, pstr, aval)
            shape = list(aval.shape)
            for i, entry in enumerate(spec):
                if entry is not None:
                    shape[i] //= dp
            total += int(np.prod(shape, dtype=np.int64)) \
                * aval.dtype.itemsize
        return total

    return (tree_bytes(abstract.params, strat.param_spec)
            + tree_bytes(abstract.model_state, strat.param_spec)
            + tree_bytes(abstract.opt_state, strat.opt_spec))


def test_fits_v4_128_target(audited):
    """BASELINE.md config #5 names v4-128 (64 chips, 32 GB each): the
    same sharding decisions at data-parallel 64 must fit with room.
    (The SPMD case targets custom meshes, covered by the v5e-8 test.)"""
    if audited["name"] == "spmd":
        pytest.skip("spmd targets custom meshes; audited on v5e-8")
    strat = STRATEGIES[audited["name"]]()
    scaled_args = _state_bytes_at_dp(strat, audited["abstract"], 64)
    g_by, u_by = _shard_factors(audited["name"], 64)
    total = scaled_args + _transient_bytes(
        audited["n_params"], 1,
        grads_sharded_by=g_by, updates_sharded_by=u_by)
    budget = HEADROOM * V4_HBM
    assert total <= budget, (
        f"{audited['name']}: {total / GB:.2f} GB vs {budget / GB:.2f} GB "
        f"on v4-128")


def _full_state_bytes(n_params: int) -> int:
    """Unsharded TrainState bytes at the documented precision recipe:
    bf16 params + fp32 master + bf16 mu + fp32 nu (+ small scalars)."""
    return n_params * (2 + 4 + 2 + 4)


def test_single_chip_cannot_train_this(audited):
    """The README's negative claim, kept honest: at data-parallel 1 the
    state plus a gradient tree (the irreducible training residents)
    exceed one v5e chip's 16 GB — this workload NEEDS the sharded
    strategies (benchmarks/README.md: 'Adam state + grads alone exceed
    16 GB HBM at 1.3B')."""
    n = audited["n_params"]
    assert _full_state_bytes(n) + 2 * n > V5E_HBM


@pytest.mark.parametrize("audited", ["zero1"], indirect=True)
def test_plugin_path_program_matches_direct_jit(audited, tmp_path):
    """Config #5 dress rehearsal THROUGH the plugin wiring (VERDICT r4
    next #8): the pod run reaches the 1.3B program via
    ``RayXlaShardedPlugin`` → ``Trainer._build_compiled``, not via the
    direct ``jax.jit`` the audit above uses — so compile (lower +
    memory_analysis, no execute) the trainer's OWN train step built
    through that wiring and assert its per-device argument bytes equal
    the direct-jit audit's exactly.  A plugin-layer regression (wrong
    strategy resolution, mesh built over the wrong devices, dropped
    in_shardings) can no longer hide behind the direct audit.

    The test drives the worker-side prefix of ``Trainer._run_stage``
    (module setup → loader build → batch peek → ``strategy.build_mesh``
    with ``plugin.local_devices()`` → ``_build_compiled``) with the
    real methods, stopping before ``_init_state`` — materializing the
    1.3B state on the CPU mesh is neither needed nor affordable here.
    """
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.core.trainer import _peek_first_batch
    from ray_lightning_tpu.plugins import RayXlaShardedPlugin

    plugin = RayXlaShardedPlugin(num_workers=1, platform="cpu")
    assert plugin.strategy.name == "zero1"
    trainer = Trainer(plugins=[plugin], default_root_dir=str(tmp_path),
                      enable_checkpointing=False, logger=False, seed=0)
    module = GPTLightningModule("gpt2-1p3b", dataset_size=2 * GLOBAL_BATCH,
                                batch_size=GLOBAL_BATCH)

    # worker-side _run_stage prefix, via the real methods
    trainer._stage = "fit"
    trainer.lightning_module = module
    module.trainer = trainer
    module.setup_model()
    strategy = trainer.plugin.strategy
    loaders = trainer._build_loaders("fit")
    example_batch, _ = _peek_first_batch(loaders["train"])
    leaves = jax.tree_util.tree_leaves(example_batch)
    batch_hint = leaves[0].shape[0] * jax.process_count()
    assert batch_hint == GLOBAL_BATCH
    trainer._mesh = strategy.build_mesh(trainer.plugin.local_devices(),
                                        batch_hint=batch_hint)
    assert dict(trainer._mesh.shape) == audited["mesh"]
    trainer._build_compiled(module, example_batch, strategy)

    comp = trainer._train_step.lower(audited["abstract"],
                                     example_batch).compile()
    got = comp.memory_analysis().argument_size_in_bytes
    assert got == audited["compiled_args"], (
        f"plugin-path program args {got / GB:.3f} GB != direct-jit audit "
        f"{audited['compiled_args'] / GB:.3f} GB")


# -- the donation SKIP region (round-5 verdict gap; ROADMAP item 5) --------
#
# On v4-64 the auto heuristic (core/trainer.py _donation_cutoff) SKIPS
# donation for the 1.3B ZeRO-1 state (~2.85 GB/device < the 0.3x cut at
# 32 GB), so the program v4 actually runs is the UN-donated one — whose
# peak carries BOTH the old state (arguments) and the new state
# (outputs, un-aliased).  The donated-program audits above do not cover
# that peak; these do.  (These tests sit at the END of the file ON
# PURPOSE: pytest groups module-scoped parametrized fixtures by param
# order of appearance, and a [zero1]-only test inserted mid-file would
# fragment the fsdp/spmd/zero1 groups and recompile the ~2 min 1.3B
# fixtures several extra times.)


@pytest.mark.parametrize("audited", ["zero1"], indirect=True)
def test_undonated_zero1_budget_in_v4_skip_region(audited):
    """Tier-1 leg: (a) v4-64 really is in the heuristic's skip region
    for this config, and (b) the un-donated residents — old state +
    un-aliased new state (the extra copy donation would have elided) +
    transients — fit 0.9 x 32 GB at data=64.  State sizes come from the
    compiled program's own memory_analysis (argument/output bytes of
    the audited fixture; aliasing changes neither), scaled to dp=64 by
    the strategy's spec walk like test_fits_v4_128_target."""
    from ray_lightning_tpu.core.trainer import Trainer

    strat = Zero1Strategy()
    state64 = _state_bytes_at_dp(strat, audited["abstract"], 64)
    # (a) the heuristic skips donation here (and the v5e-8 mesh —
    # ~2.9 GB/device state against 16 GB — donates; the decision table
    # in tests/test_trainer_local.py pins both)
    assert Trainer._donation_cutoff(state64, V4_HBM) is False, \
        f"expected v4-64 donation-skip, state {state64 / GB:.2f} GB"
    # (b) un-donated budget: outputs carry a FULL un-aliased state copy
    # on top of the argument state.  The fixture's compiled output
    # bytes confirm outputs are state-sized (metrics are scalars).
    assert audited["compiled_out"] >= 0.9 * audited["compiled_args"]
    out_over_args = audited["compiled_out"] / audited["compiled_args"]
    g_by, u_by = _shard_factors("zero1", 64)
    total = state64 * (1 + out_over_args) + _transient_bytes(
        audited["n_params"], 1,
        grads_sharded_by=g_by, updates_sharded_by=u_by)
    budget = HEADROOM * V4_HBM
    assert total <= budget, (
        f"un-donated zero1: {total / GB:.2f} GB accounted vs "
        f"{budget / GB:.2f} GB on v4-64")


@functools.lru_cache(maxsize=None)
def _audited_undonated():
    """Compile the SAME zero1 program WITHOUT donation — the
    executable the v4 skip region actually dispatches — and return its
    own ``memory_analysis``.  Memoized like ``_audited`` so the two
    tests below share one ~2 min compile."""
    audited = _audited("zero1")
    module = audited["module"]
    strat = Zero1Strategy()
    mesh = strat.build_mesh(batch_hint=GLOBAL_BATCH)
    tx = module.configure_optimizers()
    shardings = strat.state_shardings(mesh, audited["abstract"])
    jitted = jax.jit(build_train_step(module, tx),   # no donate_argnums
                     in_shardings=(shardings,
                                   strat.batch_shardings(
                                       mesh, audited["batch"])),
                     out_shardings=(shardings, None))
    return jitted.lower(audited["abstract"],
                        audited["batch"]).compile().memory_analysis()


@pytest.mark.parametrize("audited", ["zero1"], indirect=True)
def test_undonated_zero1_compile_audit(audited):
    """The ROADMAP item-5 verdict gap, closed in tier-1: the un-donated
    1.3B ZeRO-1 program's OWN ``memory_analysis`` (not numbers inferred
    from the donated fixture) pins the skip-region story — identical
    argument bytes, ZERO aliasing (the second state copy is real), a
    state-sized output — and the 2x-state residents fit v4's budget at
    data=64.  (Previously slow-gated behind a duplicate compile; the
    memoized ``_audited_undonated`` makes the direct audit affordable
    in the tier-1 window.)"""
    mem = _audited_undonated()
    assert mem.argument_size_in_bytes == audited["compiled_args"]
    assert mem.alias_size_in_bytes == 0, \
        "un-donated program must not alias state buffers"
    # the un-donated output state copy really is state-sized
    assert mem.output_size_in_bytes >= 0.9 * audited["compiled_args"]
    strat = Zero1Strategy()
    state64 = _state_bytes_at_dp(strat, audited["abstract"], 64)
    g_by, u_by = _shard_factors("zero1", 64)
    # scale the audited per-device outputs to dp=64 via the measured
    # out/args ratio so the budget uses THIS program's numbers
    out_over_args = (mem.output_size_in_bytes
                     / mem.argument_size_in_bytes)
    total = state64 * (1 + out_over_args) + _transient_bytes(
        audited["n_params"], 1, grads_sharded_by=g_by,
        updates_sharded_by=u_by)
    assert total <= HEADROOM * V4_HBM
