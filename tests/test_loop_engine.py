"""Single-epoch-engine equality: every dispatch shape (streamed,
chunked, device-cached) must produce the SAME training run on the same
data — global_step progression, metric values, callback cadence.

This is the test the round-2 trio of divergent loops needed: the cached
loop froze batch membership across epochs while a shuffling streamed
loader re-draws it (judge-flagged divergence).  The engine's cached
source now repacks the device cache from the loader's own per-epoch
index order, so shuffle runs are sequence-identical too.
"""

from __future__ import annotations

import numpy as np

from ray_lightning_tpu import Trainer
from ray_lightning_tpu.core.callbacks import Callback
from ray_lightning_tpu.core.data import ArrayDataset, DataLoader
from ray_lightning_tpu.models.boring import BoringModel


class Recorder(Callback):
    """Records the exact event sequence a run produces."""

    def __init__(self):
        self.events: list = []
        self.losses: list = []

    def on_train_batch_start(self, trainer, module, batch, idx):
        self.events.append(("start", trainer.global_step, idx))

    def on_train_batch_end(self, trainer, module, metrics, batch, idx):
        self.events.append(("end", trainer.global_step, idx))
        self.losses.extend(
            np.atleast_1d(np.asarray(metrics["loss"],
                                     np.float64)).tolist())


class ShuffledBoring(BoringModel):
    """BoringModel with a shuffling train loader (the membership case)."""

    def __init__(self, shuffle: bool, n: int = 16, batch_size: int = 2,
                 drop_last: bool = True, **kw):
        super().__init__(dataset_length=n, batch_size=batch_size)
        self._shuffle = shuffle
        self._drop_last = drop_last

    def train_dataloader(self):
        rng = np.random.default_rng(3)
        ds = ArrayDataset(rng.standard_normal((self.dataset_length, 32),
                                              dtype=np.float32))
        return DataLoader(ds, batch_size=self.batch_size,
                          shuffle=self._shuffle, seed=11,
                          drop_last=self._drop_last)


def _run(epochs=2, shuffle=False, drop_last=True, n=16, batch_size=2,
         **trainer_kw):
    rec = Recorder()
    model = ShuffledBoring(shuffle, n=n, drop_last=drop_last,
                           batch_size=batch_size)
    trainer = Trainer(max_epochs=epochs, enable_checkpointing=False,
                      num_sanity_val_steps=0, limit_val_batches=0,
                      logger=False, callbacks=[rec], seed=0, **trainer_kw)
    trainer.fit(model)
    return trainer, rec


def test_cached_matches_streamed_exactly():
    t_s, r_s = _run()
    t_c, r_c = _run(cache_train_dataset=True)
    assert t_s.global_step == t_c.global_step
    assert r_s.events == r_c.events
    np.testing.assert_allclose(r_c.losses, r_s.losses, rtol=1e-6)


def test_cached_matches_streamed_with_shuffle():
    """THE membership case: a shuffling loader re-draws batch membership
    per epoch; the cached run must follow it, not freeze epoch-0's."""
    t_s, r_s = _run(epochs=3, shuffle=True)
    t_c, r_c = _run(epochs=3, shuffle=True, cache_train_dataset=True)
    assert r_s.events == r_c.events
    np.testing.assert_allclose(r_c.losses, r_s.losses, rtol=1e-6)
    # sanity: shuffle really re-draws (else this test proves nothing)
    t_f, r_f = _run(epochs=3, shuffle=False)
    assert not np.allclose(r_f.losses, r_s.losses)


def test_chunked_matches_streamed_losses():
    """steps_per_execution coarsens callbacks by design but the loss
    SEQUENCE (one value per optimizer step) must be unchanged."""
    _, r_s = _run()
    t_k, r_k = _run(steps_per_execution=4)
    np.testing.assert_allclose(r_k.losses, r_s.losses, rtol=1e-6)
    # cadence: starts per batch, ends once per chunk
    starts = [e for e in r_k.events if e[0] == "start"]
    ends = [e for e in r_k.events if e[0] == "end"]
    assert len(starts) == len(r_s.losses)
    assert len(ends) == len(r_s.losses) // 4


def test_cached_chunked_matches_streamed_chunked():
    t_a, r_a = _run(steps_per_execution=4)
    t_b, r_b = _run(steps_per_execution=4, cache_train_dataset=True)
    assert r_a.events == r_b.events
    np.testing.assert_allclose(r_b.losses, r_a.losses, rtol=1e-6)


def test_partial_batch_routed_not_crashed():
    """drop_last=False with a ragged tail: the cache cannot hold the
    partial batch; it must ride the host single-step program — same
    sequence as streamed (round-2's cache crashed in np.stack here).
    batch_size=3 keeps the data-parallel size at 1 so the size-2 tail
    is acceptable to every path."""
    t_s, r_s = _run(drop_last=False, n=20, batch_size=3)
    t_c, r_c = _run(drop_last=False, n=20, batch_size=3,
                    cache_train_dataset=True)
    assert t_s.global_step == t_c.global_step == 14  # 2 epochs × (6+1)
    assert r_s.events == r_c.events
    np.testing.assert_allclose(r_c.losses, r_s.losses, rtol=1e-6)


def test_partial_batch_with_chunking():
    _, r_s = _run(drop_last=False, n=20, batch_size=3)
    t_k, r_k = _run(drop_last=False, n=20, batch_size=3,
                    steps_per_execution=3, cache_train_dataset=True)
    np.testing.assert_allclose(r_k.losses, r_s.losses, rtol=1e-6)


class ForeignLoaderBoring(BoringModel):
    """A generator 'loader' the cache cannot introspect."""

    def train_dataloader(self):
        rng = np.random.default_rng(3)
        data = rng.standard_normal((8, 2, 32), dtype=np.float32)

        def gen():
            for b in data:
                yield b
        return gen()


def test_unusable_cache_streams_every_epoch():
    """A foreign loader disables the cache with a warning and streams —
    and the fit must still train (round-2's failed build consumed the
    iterator and trained zero batches)."""
    rec = Recorder()
    trainer = Trainer(max_epochs=1, enable_checkpointing=False,
                      num_sanity_val_steps=0, limit_val_batches=0,
                      logger=False, callbacks=[rec], seed=0,
                      cache_train_dataset=True)
    trainer.fit(ForeignLoaderBoring())
    assert trainer.global_step == 8
    assert len(rec.losses) == 8


def test_cached_default_callbacks_skip_host_collation(monkeypatch):
    """With no callback overriding a per-batch hook, the engine must
    never materialize host batches from the cache (Item.batch unused) —
    removing per-step host work is the cached path's whole purpose
    (VERDICT r3 weak #6)."""
    from ray_lightning_tpu.core import loop_engine

    calls = {"batch": 0}
    orig = loop_engine.Item.batch

    def counting_batch(self):
        calls["batch"] += 1
        return orig(self)

    monkeypatch.setattr(loop_engine.Item, "batch", counting_batch)

    model = ShuffledBoring(True, n=16)
    trainer = Trainer(max_epochs=2, enable_checkpointing=False,
                      num_sanity_val_steps=0, limit_val_batches=0,
                      logger=False, seed=0, cache_train_dataset=True)
    trainer.fit(model)
    assert trainer.global_step == 16
    assert calls["batch"] == 0

    # and WITH a batch-hook callback the host batches flow as before
    rec = Recorder()
    model2 = ShuffledBoring(True, n=16)
    trainer2 = Trainer(max_epochs=2, enable_checkpointing=False,
                       num_sanity_val_steps=0, limit_val_batches=0,
                       logger=False, seed=0, callbacks=[rec],
                       cache_train_dataset=True)
    trainer2.fit(model2)
    assert calls["batch"] > 0
    assert len(rec.events) == 2 * 16


def test_cached_unstable_indices_without_shuffle_flag():
    """A loader whose _indices() varies per epoch WITHOUT setting
    shuffle=True must keep working — the flat device copy is dropped
    eagerly on the shuffle=False promise (peak-HBM first), and a broken
    promise triggers a warned re-upload instead of a crash
    (ADVICE r3 #2) — and must match the streamed run exactly."""

    class _SneakyLoader(DataLoader):
        def _indices(self):
            idx = super()._indices()
            # vary order per epoch while claiming shuffle=False
            return idx if self._epoch % 2 == 0 else idx[::-1]

    class _SneakyBoring(ShuffledBoring):
        def train_dataloader(self):
            rng = np.random.default_rng(3)
            ds = ArrayDataset(rng.standard_normal(
                (self.dataset_length, 32), dtype=np.float32))
            return _SneakyLoader(ds, batch_size=self.batch_size,
                                 shuffle=False, drop_last=True)

    def run(**kw):
        rec = Recorder()
        model = _SneakyBoring(False, n=16)
        trainer = Trainer(max_epochs=3, enable_checkpointing=False,
                          num_sanity_val_steps=0, limit_val_batches=0,
                          logger=False, callbacks=[rec], seed=0, **kw)
        # the engine advances loader epochs via set_epoch
        trainer.fit(model)
        return trainer, rec

    t_s, r_s = run()
    t_c, r_c = run(cache_train_dataset=True)
    assert t_c.global_step == t_s.global_step
    np.testing.assert_allclose(r_c.losses, r_s.losses, rtol=1e-6,
                               atol=1e-6)


def test_subclass_overriding_batch_hook_gets_real_batch():
    """``needs_batch = False`` belongs to the class that declares it: a
    user subclass that overrides a batch hook WITHOUT restating the flag
    must receive the real batch (its new body may read it), while the
    base class — and a subclass that restates False — keep batch=None
    (ADVICE r4 #1: resolve needs_batch against the hook-defining class).
    """
    class QuietBase(Callback):
        needs_batch = False     # this class's hook never reads the batch

        def on_train_batch_end(self, trainer, module, outputs, batch,
                               batch_idx):
            pass

    class NaiveSub(QuietBase):  # overrides, does not restate the flag
        def __init__(self):
            self.batches = []

        def on_train_batch_end(self, trainer, module, outputs, batch,
                               batch_idx):
            self.batches.append(batch)

    class DeclaredSub(NaiveSub):  # restates the promise at its own level
        needs_batch = False

    def fit(cb):
        model = ShuffledBoring(False, n=8)
        trainer = Trainer(max_epochs=1, enable_checkpointing=False,
                          num_sanity_val_steps=0, limit_val_batches=0,
                          logger=False, callbacks=[cb], seed=0,
                          cache_train_dataset=True)
        trainer.fit(model)

    naive = NaiveSub()
    fit(naive)
    assert len(naive.batches) == 4
    assert all(b is not None for b in naive.batches)

    declared = DeclaredSub()
    fit(declared)
    assert len(declared.batches) == 4
    assert all(b is None for b in declared.batches)

    # instance-assigned hook on a needs_batch=False instance: the
    # assignment is more derived than any class flag -> real batch
    grabbed = []
    patched = QuietBase()
    patched.on_train_batch_end = (
        lambda trainer, module, outputs, batch, idx:
        grabbed.append(batch))
    fit(patched)
    assert len(grabbed) == 4
    assert all(b is not None for b in grabbed)
