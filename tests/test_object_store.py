"""Shared-memory object store: write-once payloads resolved worker-side
from mapped segments (the ray.put fan-out analog, ray_ddp.py:331 /
SURVEY.md §2.2 plasma-store row)."""

import os

import numpy as np

from ray_lightning_tpu.cluster.executor import RLTExecutor
from ray_lightning_tpu.cluster.local import (
    LocalBackend,
    LocalObjectRef,
    resolve_refs,
)


def test_put_get_roundtrip():
    backend = LocalBackend()
    try:
        obj = {"a": np.arange(1000), "b": "text", "c": (1, 2.5)}
        ref = backend.put(obj)
        assert isinstance(ref, LocalObjectRef)
        assert os.path.exists(ref.path)
        got = backend.get(ref)
        np.testing.assert_array_equal(got["a"], obj["a"])
        assert got["b"] == "text" and got["c"] == (1, 2.5)
    finally:
        backend.shutdown()


def test_resolve_refs_top_level_only():
    backend = LocalBackend()
    try:
        ref = backend.put([1, 2, 3])
        args, kwargs = resolve_refs(("plain", ref, {"nested": ref}),
                                    {"kw": ref})
        assert args[0] == "plain"
        assert args[1] == [1, 2, 3]
        # nested refs stay refs (Ray deref-on-delivery parity)
        assert isinstance(args[2]["nested"], LocalObjectRef)
        # but top-level kwargs deref, as in Ray
        assert kwargs["kw"] == [1, 2, 3]
    finally:
        backend.shutdown()


def test_free_unlinks_segment():
    backend = LocalBackend()
    try:
        ref = backend.put(b"x" * 4096)
        path = ref.path
        assert os.path.exists(path)
        backend.free(ref)
        assert not os.path.exists(path)
        backend.free(ref)  # double-free is a no-op
    finally:
        backend.shutdown()


def test_shutdown_cleans_segments():
    backend = LocalBackend()
    ref = backend.put(b"y" * 4096)
    backend.shutdown()
    assert not os.path.exists(ref.path)


def test_worker_derefs_payload():
    """An actor method receiving an object ref gets the VALUE — the bytes
    arrive via the shared segment, not the socket."""
    backend = LocalBackend()
    try:
        payload = {"arr": np.arange(256), "tag": "via-shm"}
        ref = backend.put(payload)
        actor = backend.create_actor(RLTExecutor, name="store-test")
        got = actor.call(
            "execute", lambda p: (p["tag"], int(p["arr"].sum())),
            ref).result(timeout=120)
        assert got == ("via-shm", int(np.arange(256).sum()))
        actor.kill()
    finally:
        backend.shutdown()
