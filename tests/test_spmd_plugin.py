"""RayXlaSpmdPlugin: multi-axis SPMD meshes over the actor runtime —
the multi-process path behind the single-host ray_spmd_example.

The mesh spans the worker processes' devices (jax.distributed), so a
tensor axis here means the Megatron collectives cross actor
boundaries — the closest CPU-CI stand-in for cross-host ICI.
"""

import numpy as np
import pytest

from ray_lightning_tpu import RayXlaSpmdPlugin, Trainer
from ray_lightning_tpu.models.gpt import (GPTLightningModule,
                                          gpt_partition_rules)
from ray_lightning_tpu.parallel.strategy import SpmdStrategy


def test_spmd_plugin_defaults_to_spmd_strategy():
    p = RayXlaSpmdPlugin(num_workers=2)
    assert p.strategy.name == "spmd"


def test_tensor_parallel_across_actors(seed):
    """(data=2, tensor=2) mesh over 2 workers x 2 devices: GPT trains
    with Megatron-sharded params where the tensor collectives cross the
    actor/process boundary."""
    strategy = SpmdStrategy(rules=gpt_partition_rules(),
                            axis_names=("data", "tensor"),
                            axis_sizes={"tensor": 2})
    plugin = RayXlaSpmdPlugin(num_workers=2, platform="cpu",
                              devices_per_worker=2, strategy=strategy)
    module = GPTLightningModule("tiny", dataset_size=32, batch_size=8,
                                lr=1e-2)
    trainer = Trainer(plugins=[plugin], max_epochs=1,
                      num_sanity_val_steps=0, limit_val_batches=1,
                      enable_checkpointing=False, log_every_n_steps=1,
                      seed=0)
    trainer.fit(module)

    loss = float(trainer.callback_metrics["loss"])
    assert np.isfinite(loss)
    assert "val_loss" in trainer.callback_metrics
    # trained weights round-tripped to the driver (gathered full arrays)
    trained = module._trained_variables
    assert trained is not None
    wte = np.asarray(trained["params"]["wte"]["embedding"])
    assert wte.shape == (512, 64)
    assert np.isfinite(wte).all()
