"""Multi-device flash attention: the shard_map wrapper must match XLA
dot attention in value and gradient on a (data, fsdp, tensor) mesh —
batch and heads shard, the kernel runs per device (interpret mode on
CPU, the gloo-for-NCCL analog of the reference's CI)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from ray_lightning_tpu.ops.attention import (
    dot_product_attention,
    sharded_flash_attention,
)


@pytest.fixture
def mesh222():
    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    return Mesh(devs, ("data", "fsdp", "tensor"))


def _qkv(B=4, T=32, H=4, D=8, key=0):
    ks = jax.random.split(jax.random.PRNGKey(key), 3)
    return tuple(jax.random.normal(k, (B, T, H, D), jnp.float32)
                 for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_values_match_dot(mesh222, causal, seed):
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal, dtype=jnp.float32)
    out = sharded_flash_attention(q, k, v, mesh=mesh222, causal=causal,
                                  dtype=jnp.float32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_gradients_match_dot(mesh222, seed):
    q, k, v = _qkv(key=1)

    def loss_ref(q, k, v):
        return (dot_product_attention(
            q, k, v, causal=True, dtype=jnp.float32) ** 2).sum()

    def loss_sharded(q, k, v):
        return (sharded_flash_attention(
            q, k, v, mesh=mesh222, causal=True, dtype=jnp.float32,
            interpret=True) ** 2).sum()

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gs = jax.grad(loss_sharded, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3)


def test_data_only_mesh(seed):
    """Meshes without a tensor axis shard batch only."""
    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("data",))
    q, k, v = _qkv(B=4, key=2)
    ref = dot_product_attention(q, k, v, causal=True, dtype=jnp.float32)
    out = sharded_flash_attention(q, k, v, mesh=mesh, causal=True,
                                  dtype=jnp.float32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)
