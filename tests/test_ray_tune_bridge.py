"""Bridge tests: the Tune callbacks must deliver into a *real* Ray
Tune/Train session when one is live (VERDICT round-1 missing #1).

Ray is not installed in the image, so the two API generations are
emulated with stub modules carrying exactly the surface the bridge
binds: classic ``ray.tune.is_session_enabled/report/checkpoint_dir``
(the reference's own call sites, reference tune.py:130-134, :161-178)
and modern ``ray.train.report(metrics, checkpoint=...)``.  The real-Ray
CI job (.github/workflows/test.yaml ray-integration) runs the same
callbacks against genuine Ray Tune.
"""

import contextlib
import os
import sys
import types

import pytest
from flax import serialization

from ray_lightning_tpu import Trainer
from ray_lightning_tpu import tune
from ray_lightning_tpu.models import BoringModel


def _fit(callback, **trainer_kwargs):
    module = BoringModel()
    trainer = Trainer(
        max_epochs=2, limit_train_batches=4, limit_val_batches=2,
        num_sanity_val_steps=0, enable_checkpointing=False,
        callbacks=[callback], **trainer_kwargs)
    trainer.fit(module)
    return trainer


@pytest.fixture
def classic_session(monkeypatch, tmp_path):
    """Stub of Ray's classic function-trainable session: live
    ``is_session_enabled``, recording ``report``/``checkpoint_dir``."""
    state = {"reports": [], "ckpt_dirs": []}
    ray = types.ModuleType("ray")
    tune_mod = types.ModuleType("ray.tune")
    tune_mod.is_session_enabled = lambda: True

    def report(**metrics):
        state["reports"].append(metrics)

    @contextlib.contextmanager
    def checkpoint_dir(step):
        d = tmp_path / f"checkpoint_{step:06d}"
        d.mkdir(parents=True, exist_ok=True)
        state["ckpt_dirs"].append(str(d))
        yield str(d)

    tune_mod.report = report
    tune_mod.checkpoint_dir = checkpoint_dir
    ray.tune = tune_mod
    monkeypatch.setitem(sys.modules, "ray", ray)
    monkeypatch.setitem(sys.modules, "ray.tune", tune_mod)
    return state


@pytest.fixture
def modern_session(monkeypatch):
    """Stub of the modern Ray Train API: a live internal session,
    ``train.report(metrics, checkpoint=...)`` and ``Checkpoint``."""
    state = {"reports": []}
    ray = types.ModuleType("ray")
    train_mod = types.ModuleType("ray.train")
    internal = types.ModuleType("ray.train._internal")
    session_mod = types.ModuleType("ray.train._internal.session")
    session_mod.get_session = lambda: object()

    class Checkpoint:
        def __init__(self, path):
            self.path = path

        @classmethod
        def from_directory(cls, path):
            return cls(path)

    def report(metrics, checkpoint=None):
        files = {}
        if checkpoint is not None:
            # snapshot before the bridge reclaims the staging dir
            for name in os.listdir(checkpoint.path):
                with open(os.path.join(checkpoint.path, name), "rb") as f:
                    files[name] = f.read()
        state["reports"].append({"metrics": metrics, "files": files})

    train_mod.report = report
    train_mod.Checkpoint = Checkpoint
    ray.train = train_mod
    for name, mod in [("ray", ray), ("ray.train", train_mod),
                      ("ray.train._internal", internal),
                      ("ray.train._internal.session", session_mod)]:
        monkeypatch.setitem(sys.modules, name, mod)
    return state


@pytest.fixture
def context_session(monkeypatch):
    """Stub of the PUBLIC context API generation (newer ray):
    ``tune.get_context()`` with a live trial id, ``tune.report(metrics,
    checkpoint=...)`` positional-dict signature, ``tune.Checkpoint``.
    No ``is_session_enabled`` and no ``ray.train._internal`` — the
    generation where both older surfaces are gone."""
    state = {"reports": []}
    ray = types.ModuleType("ray")
    tune_mod = types.ModuleType("ray.tune")

    class _Ctx:
        def get_trial_id(self):
            return "trial_0001"

    tune_mod.get_context = lambda: _Ctx()

    class Checkpoint:
        def __init__(self, path):
            self.path = path

        @classmethod
        def from_directory(cls, path):
            return cls(path)

    def report(metrics, checkpoint=None):
        files = {}
        if checkpoint is not None:
            for name in os.listdir(checkpoint.path):
                with open(os.path.join(checkpoint.path, name), "rb") as f:
                    files[name] = f.read()
        state["reports"].append({"metrics": metrics, "files": files})

    tune_mod.report = report
    tune_mod.Checkpoint = Checkpoint
    ray.tune = tune_mod
    monkeypatch.setitem(sys.modules, "ray", ray)
    monkeypatch.setitem(sys.modules, "ray.tune", tune_mod)
    return state


def test_classic_report_lands_in_ray_session(classic_session, seed):
    _fit(tune.TuneReportCallback(on="validation_end"))
    assert len(classic_session["reports"]) == 2
    for r in classic_session["reports"]:
        assert "val_loss" in r


def test_classic_checkpoint_then_report(classic_session, seed):
    """TuneReportCheckpointCallback inside a (stubbed) genuine Ray Tune
    trial records both the checkpoint and the metric, checkpoint first
    so Tune associates it with the reported iteration."""
    _fit(tune.TuneReportCheckpointCallback(on="validation_end"))
    assert len(classic_session["reports"]) == 2
    assert len(classic_session["ckpt_dirs"]) == 2
    for d in classic_session["ckpt_dirs"]:
        path = os.path.join(d, "checkpoint")
        assert os.path.isfile(path)
    ckpt = Trainer.load_checkpoint_dict(path)
    assert ckpt["global_step"] > 0 and "state" in ckpt


def test_modern_report_attaches_staged_checkpoint(modern_session, seed):
    """Under the modern Train API a checkpoint can only ride a report:
    the staged blob must arrive attached to the next report, and the
    staging dir must be reclaimed."""
    _fit(tune.TuneReportCheckpointCallback(on="validation_end"))
    reports = modern_session["reports"]
    assert len(reports) == 2
    for r in reports:
        assert "val_loss" in r["metrics"]
        blob = r["files"]["checkpoint"]
        ckpt = serialization.msgpack_restore(blob)
        assert ckpt["global_step"] > 0 and "state" in ckpt


def test_modern_plain_report_without_checkpoint(modern_session, seed):
    _fit(tune.TuneReportCallback(on="validation_end"))
    reports = modern_session["reports"]
    assert len(reports) == 2
    assert all(r["files"] == {} for r in reports)


def test_context_report_lands_in_public_api(context_session, seed):
    """The public get_context() generation delivers reports — the leg
    that keeps working when a release drops both is_session_enabled and
    ray.train._internal (VERDICT r2 missing #2)."""
    _fit(tune.TuneReportCallback(on="validation_end"))
    reports = context_session["reports"]
    assert len(reports) == 2
    for r in reports:
        assert "val_loss" in r["metrics"]
        assert r["files"] == {}


def test_context_report_attaches_staged_checkpoint(context_session, seed):
    _fit(tune.TuneReportCheckpointCallback(on="validation_end"))
    reports = context_session["reports"]
    assert len(reports) == 2
    for r in reports:
        blob = r["files"]["checkpoint"]
        ckpt = serialization.msgpack_restore(blob)
        assert ckpt["global_step"] > 0 and "state" in ckpt


def test_probe_order_classic_beats_context(classic_session, monkeypatch,
                                           seed):
    """Transitional Ray versions expose BOTH is_session_enabled and
    get_context: the classic leg (the reference's own surface) must win,
    and the context report signature must never be hit."""
    tune_mod = sys.modules["ray.tune"]

    class _Ctx:
        def get_trial_id(self):
            return "trial_0001"

    hits = {"context": 0}
    real_report = tune_mod.report

    def guarded_report(*args, **kwargs):
        if args:  # positional dict = context-generation signature
            hits["context"] += 1
        return real_report(*args, **kwargs)

    monkeypatch.setattr(tune_mod, "get_context", lambda: _Ctx(),
                        raising=False)
    monkeypatch.setattr(tune_mod, "report", guarded_report)
    _fit(tune.TuneReportCallback(on="validation_end"))
    assert len(classic_session["reports"]) == 2
    assert hits["context"] == 0


def test_probe_order_context_beats_private_session(context_session,
                                                   monkeypatch, seed):
    """When both the public context and the private train session exist,
    the PUBLIC surface must be used (the private one may vanish)."""
    internal = types.ModuleType("ray.train._internal")
    session_mod = types.ModuleType("ray.train._internal.session")
    session_mod.get_session = lambda: object()
    train_mod = types.ModuleType("ray.train")
    hits = {"private": 0}

    def private_report(*a, **k):
        hits["private"] += 1

    train_mod.report = private_report
    sys.modules["ray"].train = train_mod
    for name, mod in [("ray.train", train_mod),
                      ("ray.train._internal", internal),
                      ("ray.train._internal.session", session_mod)]:
        monkeypatch.setitem(sys.modules, name, mod)
    _fit(tune.TuneReportCallback(on="validation_end"))
    assert len(context_session["reports"]) == 2
    assert hits["private"] == 0


def test_builtin_session_still_wins_over_context(context_session, tmp_path,
                                                 seed):
    """Probe order root: the builtin runner's thread-local session beats
    every bridge generation (a nested builtin sweep must not leak
    reports into an outer real-Ray trial)."""
    analysis = tune.run(
        lambda config: tune.report(loss=1.0),
        config={}, num_samples=1, metric="loss", mode="min",
        local_dir=str(tmp_path))
    assert analysis.trials[0].last_result["loss"] == 1.0
    assert context_session["reports"] == []


def test_builtin_session_still_wins(classic_session, tmp_path, seed):
    """The builtin runner's thread-local session takes precedence over
    any ambient real-Ray session (a nested builtin sweep must not leak
    reports into an outer Ray trial)."""
    analysis = tune.run(
        lambda config: tune.report(loss=1.0),
        config={}, num_samples=1, metric="loss", mode="min",
        local_dir=str(tmp_path))
    assert analysis.trials[0].last_result["loss"] == 1.0
    assert classic_session["reports"] == []


@pytest.mark.slow
def test_classic_session_through_actor_queue(classic_session, seed,
                                             monkeypatch):
    """The §3.3 grandchild relay against a REAL-Ray-style session:
    training runs in actor subprocesses, the report payload rides the
    worker→driver queue, and executes driver-side into the (stubbed)
    genuine ray.tune session — the reference's exact topology
    (tune.py:130-134 + util.py:47-52)."""
    monkeypatch.setenv("RLT_BACKEND", "local")
    from ray_lightning_tpu import RayXlaPlugin

    _fit(tune.TuneReportCallback(on="validation_end"),
         plugins=[RayXlaPlugin(num_workers=2, platform="cpu")])
    assert len(classic_session["reports"]) == 2
    for r in classic_session["reports"]:
        assert "val_loss" in r


@pytest.mark.slow
def test_classic_checkpoint_through_actor_queue(classic_session, seed,
                                                monkeypatch):
    """Checkpoint bytes assembled on remote rank 0 ride the queue and
    land in the (stubbed) genuine ray.tune checkpoint_dir, checkpoint
    before report (reference tune.py:161-178, :234-236)."""
    monkeypatch.setenv("RLT_BACKEND", "local")
    from ray_lightning_tpu import RayXlaPlugin

    _fit(tune.TuneReportCheckpointCallback(on="validation_end"),
         plugins=[RayXlaPlugin(num_workers=2, platform="cpu")])
    assert len(classic_session["reports"]) == 2
    assert len(classic_session["ckpt_dirs"]) == 2
    path = os.path.join(classic_session["ckpt_dirs"][-1], "checkpoint")
    ckpt = Trainer.load_checkpoint_dict(path)
    assert ckpt["global_step"] > 0 and "state" in ckpt


@pytest.fixture
def midgen_session(monkeypatch):
    """Stub of a MID-generation Ray: ``tune.get_context`` exists (so the
    context probe fires) but ``tune.report`` still has the classic
    kwargs-only signature — calling it with a positional dict would
    TypeError (ADVICE r3 #3).  No ``is_session_enabled``."""
    state = {"kw_reports": [], "train_reports": []}
    ray = types.ModuleType("ray")
    tune_mod = types.ModuleType("ray.tune")

    class _Ctx:
        def get_trial_id(self):
            return "trial_0001"

    tune_mod.get_context = lambda: _Ctx()

    def kw_report(**kwargs):
        state["kw_reports"].append(kwargs)

    tune_mod.report = kw_report
    ray.tune = tune_mod
    monkeypatch.setitem(sys.modules, "ray", ray)
    monkeypatch.setitem(sys.modules, "ray.tune", tune_mod)
    return state


def test_midgen_report_falls_back_to_kwargs(midgen_session, seed):
    """Context live + kwargs-only tune.report + no train session: the
    bridge must deliver metrics classic-style instead of raising
    TypeError mid-trial."""
    _fit(tune.TuneReportCallback(on="validation_end"))
    assert len(midgen_session["kw_reports"]) == 2
    for r in midgen_session["kw_reports"]:
        assert "val_loss" in r


def test_midgen_prefers_train_session_for_checkpoints(midgen_session,
                                                      monkeypatch, seed):
    """Context live + kwargs-only tune.report + a train session present:
    reports (and staged checkpoints) must route through train.report —
    the only generation-appropriate surface that can attach them."""
    internal = types.ModuleType("ray.train._internal")
    session_mod = types.ModuleType("ray.train._internal.session")
    session_mod.get_session = lambda: object()
    train_mod = types.ModuleType("ray.train")

    class Checkpoint:
        def __init__(self, path):
            self.path = path

        @classmethod
        def from_directory(cls, path):
            return cls(path)

    def train_report(metrics, checkpoint=None):
        files = {}
        if checkpoint is not None:
            for name in os.listdir(checkpoint.path):
                with open(os.path.join(checkpoint.path, name), "rb") as f:
                    files[name] = f.read()
        midgen_session["train_reports"].append(
            {"metrics": metrics, "files": files})

    train_mod.report = train_report
    train_mod.Checkpoint = Checkpoint
    sys.modules["ray"].train = train_mod
    for name, mod in [("ray.train", train_mod),
                      ("ray.train._internal", internal),
                      ("ray.train._internal.session", session_mod)]:
        monkeypatch.setitem(sys.modules, name, mod)
    _fit(tune.TuneReportCheckpointCallback(on="validation_end"))
    assert midgen_session["kw_reports"] == []
    reports = midgen_session["train_reports"]
    assert len(reports) == 2
    for r in reports:
        assert "val_loss" in r["metrics"]
        blob = r["files"]["checkpoint"]
        ckpt = serialization.msgpack_restore(blob)
        assert ckpt["global_step"] > 0 and "state" in ckpt


def test_midgen_staged_checkpoint_lands_in_classic_dir(midgen_session,
                                                       monkeypatch, seed,
                                                       tmp_path):
    """Mid-generation with classic tune.checkpoint_dir still present:
    a staged checkpoint must be written there (not silently dropped)
    when the kwargs-only report goes out."""
    tune_mod = sys.modules["ray.tune"]
    dirs = []

    @contextlib.contextmanager
    def checkpoint_dir(step):
        d = tmp_path / f"ckpt_{step}_{len(dirs)}"
        d.mkdir()
        dirs.append(str(d))
        yield str(d)

    tune_mod.checkpoint_dir = checkpoint_dir
    _fit(tune.TuneReportCheckpointCallback(on="validation_end"))
    assert len(midgen_session["kw_reports"]) == 2
    assert len(dirs) == 2
    for d in dirs:
        path = os.path.join(d, "checkpoint")
        assert os.path.isfile(path)
    ckpt = Trainer.load_checkpoint_dict(path)
    assert ckpt["global_step"] > 0 and "state" in ckpt
