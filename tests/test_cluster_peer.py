"""Worker↔worker peer channel (cluster/peer.py + backend routing).

The cluster backends' third data plane, added for the MPMD pipeline's
activation exchange (tests/test_mpmd.py covers that consumer):
tag-addressed mailboxes are out-of-order safe, dead-peer waits raise
naming the waiter instead of hanging, and the builtin backend routes
peer frames driver-side so a payload arrives WHILE the receiving
actor's main thread is busy inside a call (the worker_main reader
thread — without it the MPMD stage shape deadlocks).
"""

from __future__ import annotations

import sys

import cloudpickle
import pytest

from ray_lightning_tpu.cluster.peer import Mailbox, PeerTimeout

# the worker subprocess cannot import this test module by name; ship
# the actor class by value instead (cloudpickle's documented seam)
cloudpickle.register_pickle_by_value(sys.modules[__name__])


def test_mailbox_out_of_order_delivery():
    box = Mailbox()
    tags = [("fwd", 1, m, 0) for m in range(4)]
    for t in reversed(tags):          # arrive in reverse order
        box.put(t, f"mb{t[2]}")
    for m, t in enumerate(tags):      # consumed in schedule order
        assert box.take(t, 1.0) == f"mb{m}"


def test_dead_peer_timeout_names_waiter_and_payload():
    box = Mailbox()
    with pytest.raises(PeerTimeout) as ei:
        box.take(("fwd", 1, 2, 7), 0.05,
                 who="stage rank 1 (chunk 1)", src="chunk 0")
    msg = str(ei.value)
    assert "stage rank 1" in msg and "chunk 0" in msg
    assert "'fwd'" in msg and "2" in msg   # what was missing, from whom


def test_retry_backoff_rewaits_and_eventually_succeeds(monkeypatch):
    """RLT_PEER_RETRIES re-waits with backoff: a payload that arrives
    during the SECOND attempt is delivered instead of raising."""
    import threading
    import time

    monkeypatch.setenv("RLT_PEER_RETRIES", "3")
    monkeypatch.setenv("RLT_PEER_BACKOFF_S", "0.01")
    box = Mailbox()

    def late_put():
        time.sleep(0.25)
        box.put(("late",), "made it")

    t = threading.Thread(target=late_put)
    t.start()
    try:
        assert box.take(("late",), 0.1) == "made it"
    finally:
        t.join()


def test_retry_budget_exhaustion_names_attempt_count(monkeypatch):
    monkeypatch.setenv("RLT_PEER_RETRIES", "2")
    monkeypatch.setenv("RLT_PEER_BACKOFF_S", "0.01")
    box = Mailbox()
    with pytest.raises(PeerTimeout, match="3 attempt"):
        box.take(("never",), 0.02, who="retry waiter")


def test_default_policy_is_single_attempt(monkeypatch):
    monkeypatch.delenv("RLT_PEER_RETRIES", raising=False)
    box = Mailbox()
    with pytest.raises(PeerTimeout, match="1 attempt"):
        box.take(("never",), 0.02)


class _PeerActor:
    """Minimal peer-channel participant: blocks inside a call waiting
    for a payload (proving delivery does not need the main thread),
    or sends one to a named peer."""

    def ping(self):
        return "pong"

    def deposit_escrow(self, item):
        from ray_lightning_tpu.cluster import worker_state
        worker_state.escrow_set(item)
        return True

    def block_forever(self):
        import time
        while True:
            time.sleep(3600)

    def wait_for(self, tag, timeout):
        from ray_lightning_tpu.cluster import worker_state
        return worker_state.peer_mailbox().take(
            tuple(tag), timeout, who="receiver actor")

    def send_to(self, dst_name, tag, payload):
        from ray_lightning_tpu.cluster import worker_state
        worker_state.peer_send(dst_name, {"tag": tuple(tag),
                                          "wire": payload})
        return True


def test_local_backend_routes_peer_frames_mid_call():
    """End-to-end over real subprocess actors: B's payload reaches A's
    mailbox while A is BLOCKED inside ``wait_for`` — driver-side
    routing (LocalBackend.peer_route) + the worker frame-reader thread
    working together.  A second payload sent before anyone waits
    proves buffering (out-of-order arrival is a mailbox no-op)."""
    from ray_lightning_tpu.cluster.local import LocalBackend

    backend = LocalBackend()
    try:
        a = backend.create_actor(_PeerActor, name="peer-a")
        b = backend.create_actor(_PeerActor, name="peer-b")
        assert a.call("ping").result(timeout=60) == "pong"
        assert b.call("ping").result(timeout=60) == "pong"

        # A blocks first; B delivers into the blocked call
        fut = a.call("wait_for", ("fwd", 0, 0, 0), 30.0)
        assert b.call("send_to", "peer-a", ("fwd", 0, 0, 0),
                      {"h": [1, 2, 3]}).result(timeout=60)
        assert fut.result(timeout=60) == {"h": [1, 2, 3]}

        # buffered delivery: payload lands before the receive starts
        assert a.call("send_to", "peer-b", ("bwd", 1, 3, 0),
                      "grad").result(timeout=60)
        assert b.call("wait_for", ("bwd", 1, 3, 0),
                      30.0).result(timeout=60) == "grad"

        # unknown destination: dropped driver-side, receiver times out
        # with the named-waiter error instead of hanging
        assert a.call("send_to", "peer-nobody", ("fwd", 9, 9, 9),
                      "lost").result(timeout=60)
        with pytest.raises(Exception, match="receiver actor"):
            a.call("wait_for", ("never", 0, 0, 0), 0.2).result(
                timeout=60)
    finally:
        backend.shutdown()


def test_escrow_harvest_bypasses_a_wedged_main_thread():
    """The zero-replay prerequisite (elastic/redundancy.py): the
    driver can fetch a worker's recovery escrow WHILE its main thread
    is stuck — the frame-reader thread answers ``escrow`` frames
    directly.  A worker that never escrowed answers None."""
    from ray_lightning_tpu.cluster.local import LocalBackend

    backend = LocalBackend()
    try:
        a = backend.create_actor(_PeerActor, name="escrow-a")
        assert a.call("ping").result(timeout=60) == "pong"
        assert a.harvest_escrow(timeout=20) is None   # nothing yet
        assert a.call("deposit_escrow",
                      {"step": 7, "rank": 0}).result(timeout=60)
        # wedge the main thread, then harvest around it
        a.call("block_forever")
        esc = a.harvest_escrow(timeout=20)
        assert esc == {"step": 7, "rank": 0}
    finally:
        backend.shutdown()
