"""Chunked cross-entropy must match the full-vocab loss in value and
gradient — it is a memory optimization, not a semantics change."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_lightning_tpu.ops.losses import chunked_softmax_cross_entropy


@pytest.mark.parametrize("n_chunks", [1, 2, 4, 5])
def test_matches_full_vocab_ce(n_chunks, seed):
    B, T, D, V = 2, 8, 16, 64
    rng = jax.random.PRNGKey(0)
    kh, kt, ky = jax.random.split(rng, 3)
    hidden = jax.random.normal(kh, (B, T, D), jnp.float32)
    table = jax.random.normal(kt, (V, D), jnp.float32)
    targets = jax.random.randint(ky, (B, T), 0, V)

    full = optax.softmax_cross_entropy_with_integer_labels(
        jnp.einsum("btd,vd->btv", hidden, table), targets).mean()
    chunked = chunked_softmax_cross_entropy(hidden, table, targets,
                                            n_chunks)
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(full),
                               rtol=1e-5)


def test_gradients_match(seed):
    B, T, D, V = 2, 8, 16, 64
    rng = jax.random.PRNGKey(1)
    kh, kt, ky = jax.random.split(rng, 3)
    hidden = jax.random.normal(kh, (B, T, D), jnp.float32)
    table = jax.random.normal(kt, (V, D), jnp.float32)
    targets = jax.random.randint(ky, (B, T), 0, V)

    def full(h, w):
        return optax.softmax_cross_entropy_with_integer_labels(
            jnp.einsum("btd,vd->btv", h, w), targets).mean()

    def chunked(h, w):
        return chunked_softmax_cross_entropy(h, w, targets, 4)

    gf = jax.grad(full, argnums=(0, 1))(hidden, table)
    gc = jax.grad(chunked, argnums=(0, 1))(hidden, table)
    for a, b in zip(gf, gc):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_indivisible_chunks_fall_back(seed):
    """n_chunks not dividing B*T degrades to the largest divisor."""
    hidden = jnp.ones((1, 6, 4))
    table = jnp.ones((8, 4))
    targets = jnp.zeros((1, 6), jnp.int32)
    out = chunked_softmax_cross_entropy(hidden, table, targets, 4)
    assert np.isfinite(float(out))


def test_gpt_config_flag_routes_to_chunked(tmp_path, seed):
    """GPTConfig.chunked_ce opts the module's loss into the chunked path
    with matching results (the gpt2-1p3b config relies on this)."""
    import dataclasses
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.models.gpt import CONFIGS, GPTLightningModule

    losses = {}
    for n in (0, 2):
        cfg = dataclasses.replace(CONFIGS["tiny"], chunked_ce=n)
        module = GPTLightningModule(cfg, dataset_size=32, batch_size=4)
        trainer = Trainer(max_epochs=1, limit_train_batches=4,
                          limit_val_batches=0, num_sanity_val_steps=0,
                          enable_checkpointing=False, seed=0,
                          default_root_dir=str(tmp_path / str(n)))
        trainer.fit(module)
        losses[n] = trainer.callback_metrics["loss"]
    np.testing.assert_allclose(losses[0], losses[2], rtol=1e-4)


def test_gpt_hidden_plus_chunked_matches_call(seed):
    """GPT.hidden + chunked CE == GPT.__call__ + full CE."""
    from ray_lightning_tpu.models.gpt import CONFIGS, GPT
    cfg = CONFIGS["tiny"]
    model = GPT(cfg)
    tok = np.arange(2 * 16, dtype=np.int32).reshape(2, 16) % cfg.vocab_size
    v = model.init(jax.random.PRNGKey(0), tok)
    tgt = np.roll(tok, -1, axis=1).astype(np.int32)

    logits = model.apply(v, tok, True)
    full = optax.softmax_cross_entropy_with_integer_labels(
        logits, tgt).mean()
    h = model.apply(v, tok, True, method=GPT.hidden)
    table = model.apply(v, method=lambda m: m.embedding_table)
    chunked = chunked_softmax_cross_entropy(h, table, tgt, 4)
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(full),
                               rtol=2e-2, atol=2e-3)


# ---------------------------------------------------------------------------
# fused_lm_cross_entropy: the default full-vocab path (bf16-resident
# logits, fp32 in-fusion accumulation) — must match the naive path in
# value and gradient; it is an HBM-traffic optimization, not semantics.
# ---------------------------------------------------------------------------

def _naive(h, w, targets):
    from jax import numpy as jnp
    logits = jnp.einsum("btd,vd->btv", h, w).astype(jnp.float32)
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, targets).mean()


def test_fused_ce_matches_full_vocab_fp32(seed):
    from ray_lightning_tpu.ops.losses import fused_lm_cross_entropy
    B, T, D, V = 2, 8, 16, 64
    kh, kt, ky = jax.random.split(jax.random.PRNGKey(2), 3)
    hidden = jax.random.normal(kh, (B, T, D), jnp.float32)
    table = jax.random.normal(kt, (V, D), jnp.float32)
    targets = jax.random.randint(ky, (B, T), 0, V)
    np.testing.assert_allclose(
        np.asarray(fused_lm_cross_entropy(hidden, table, targets)),
        np.asarray(_naive(hidden, table, targets)), rtol=1e-5)


def test_fused_ce_gradients_match_fp32(seed):
    from ray_lightning_tpu.ops.losses import fused_lm_cross_entropy
    B, T, D, V = 2, 8, 16, 64
    kh, kt, ky = jax.random.split(jax.random.PRNGKey(3), 3)
    hidden = jax.random.normal(kh, (B, T, D), jnp.float32)
    table = jax.random.normal(kt, (V, D), jnp.float32)
    targets = jax.random.randint(ky, (B, T), 0, V)
    gf = jax.grad(_naive, argnums=(0, 1))(hidden, table, targets)
    gz = jax.grad(fused_lm_cross_entropy, argnums=(0, 1))(
        hidden, table, targets)
    for a, b in zip(gf, gz):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=1e-6)


def test_fused_ce_matches_naive_bf16(seed):
    """In the compute dtype the two paths share the bf16 matmul rounding;
    values agree to bf16-level tolerance."""
    from ray_lightning_tpu.ops.losses import fused_lm_cross_entropy
    B, T, D, V = 2, 16, 32, 128
    kh, kt, ky = jax.random.split(jax.random.PRNGKey(4), 3)
    hidden = jax.random.normal(kh, (B, T, D), jnp.bfloat16)
    table = jax.random.normal(kt, (V, D), jnp.bfloat16)
    targets = jax.random.randint(ky, (B, T), 0, V)
    fused = float(fused_lm_cross_entropy(hidden, table, targets))
    ref = float(_naive(hidden.astype(jnp.float32),
                       table.astype(jnp.float32), targets))
    assert abs(fused - ref) < 0.05 * max(1.0, abs(ref))
