"""Metrics plane (telemetry/metrics.py + exporter.py): typed per-rank
instruments, device/collective byte accounting, driver-side bandwidth
derivation, and the live Prometheus endpoint.

The e2e case mirrors the acceptance bar: a 2-worker local-backend fit
with telemetry on must make ``GET /metrics`` on the driver return a
Prometheus exposition with per-rank step-time histogram, HBM gauges and
per-op collective byte counters, and the exported ``metrics.jsonl`` +
summary must carry per-op achieved bandwidth (GiB/s).
"""

import json
import logging
import re
import threading
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu import Trainer, telemetry
from ray_lightning_tpu.models import BoringModel
from ray_lightning_tpu.telemetry import metrics as M
from ray_lightning_tpu.telemetry.aggregator import TelemetryAggregator
from ray_lightning_tpu.telemetry.exporter import (
    MetricsHTTPServer,
    render_prometheus,
    render_status,
)
from ray_lightning_tpu.telemetry.heartbeat import make_heartbeat

from tests.utils import cpu_plugin


@pytest.fixture(autouse=True)
def _clean_metrics():
    """Registry and recorder are process-ambient; never leak them."""
    yield
    telemetry.disable_metrics()
    telemetry.disable()
    telemetry.set_active(None)


# -- instrument name convention (satellite: Prometheus-clean lint) ------

def test_name_validation_accepts_core_and_rejects_dirty():
    for name in M.CORE_METRICS:
        assert M.validate_metric_name(name) == name
    for bad in ("steps_total",            # missing rlt_ prefix
                "rlt_StepTime_seconds",   # uppercase
                "rlt_hbm",                # no unit suffix
                "rlt_collective-bytes",   # dash
                "rlt_steps_count"):       # unknown suffix
        with pytest.raises(ValueError):
            M.validate_metric_name(bad)


def test_lint_covers_every_registered_name_in_tree():
    # the same walk format.sh --check runs: every counter()/gauge()/
    # histogram() literal in the package must be clean
    assert M.lint_metric_names() == []


def test_anatomy_series_covered_by_lint():
    """Every rlt_anatomy_* series the anatomy controller publishes is a
    declared CORE metric (so the name lint owns the full surface)."""
    assert {"rlt_anatomy_compute_seconds",
            "rlt_anatomy_collective_seconds",
            "rlt_anatomy_exposed_seconds",
            "rlt_anatomy_host_seconds",
            "rlt_anatomy_dcn_seconds",
            "rlt_anatomy_windows_total"} <= set(M.CORE_METRICS)


def test_lint_flags_dirty_registration(tmp_path):
    (tmp_path / "mod.py").write_text(
        'reg.counter("torch_steps")\n')
    problems = M.lint_metric_names(str(tmp_path))
    assert len(problems) == 1 and "torch_steps" in problems[0]


# -- typed instruments ---------------------------------------------------

def test_counter_gauge_label_sets():
    reg = M.MetricsRegistry()
    c = reg.counter("rlt_collective_bytes_total")
    c.inc(10, op="gather")
    c.inc(5, op="gather")
    c.inc(7, op="ring")
    assert c.value(op="gather") == 15 and c.value(op="ring") == 7
    g = reg.gauge("rlt_hbm_bytes")
    g.set(100, device="0")
    g.set(42, device="0")        # gauge: set, not add
    assert g.value(device="0") == 42
    snap = {(m["name"], tuple(sorted(m["labels"].items()))): m["value"]
            for m in reg.snapshot()}
    assert snap[("rlt_collective_bytes_total", (("op", "gather"),))] == 15


def test_histogram_prometheus_bucket_semantics():
    h = M.Histogram("rlt_step_time_seconds", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    (snap,) = h.snapshot()
    assert snap["counts"] == [1, 2, 1]        # <=0.1, <=1.0, +Inf
    assert snap["count"] == 4
    assert snap["sum"] == pytest.approx(6.05)


def test_registry_rejects_type_conflicts():
    reg = M.MetricsRegistry()
    reg.counter("rlt_steps_total")
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("rlt_steps_total")


def test_disabled_entry_points_are_noops():
    assert not M.metrics_enabled()
    M.record_collective("gather", 123)          # must not raise
    M.note_traced_collective("ring", 456)
    M.on_step(0.01)
    M.on_compile()
    M.on_data_wait(0.001)
    assert M.metrics_brief() is None


# -- collective accounting ----------------------------------------------

def test_record_collective_bytes_ops_seconds():
    reg = telemetry.enable_metrics(pump=False)
    M.record_collective("gather", 1000, seconds=0.5)
    M.record_collective("gather", 1000, seconds=0.5)
    assert reg.counter("rlt_collective_bytes_total").value(op="gather") \
        == 2000
    assert reg.counter("rlt_collective_ops_total").value(op="gather") == 2
    assert reg.counter("rlt_collective_seconds_total").value(op="gather") \
        == pytest.approx(1.0)
    assert reg.last_collective == "gather"


def test_traced_collectives_charged_per_executed_step():
    reg = telemetry.enable_metrics(pump=False)
    M.note_traced_collective("ring", 100)
    M.note_traced_collective("ring", 128)     # re-trace overwrites
    M.note_step_collectives({"grad_reduce_scatter": 64,
                             "param_all_gather": 64,
                             "empty": 0})     # zero-cost ops dropped
    M.on_step(0.01, k=3, step=3)
    bytes_c = reg.counter("rlt_collective_bytes_total")
    assert bytes_c.value(op="ring") == 128 * 3
    assert bytes_c.value(op="grad_reduce_scatter") == 64 * 3
    assert bytes_c.value(op="empty") == 0
    assert reg.counter("rlt_collective_ops_total").value(op="ring") == 3
    assert reg.counter("rlt_steps_total").value() == 3
    assert reg.current_step == 3


def test_dcn_bytes_charged_per_executed_step():
    """The hierarchical comm plane's DCN-crossing share (op suffixes →
    comm/audit.py declared_dcn_bytes) lands on its own counter, charged
    per step like the traced collectives."""
    from ray_lightning_tpu.comm.audit import declared_dcn_bytes

    reg = telemetry.enable_metrics(pump=False)
    ops = {"grad_all_reduce_dcn": 40, "grad_all_reduce_ici": 400}
    M.note_step_collectives(ops, dcn_bytes=declared_dcn_bytes(ops, True))
    M.on_step(0.01, k=2, step=2)
    assert reg.counter("rlt_comm_dcn_bytes_total").value() == 40 * 2
    # the exposed gauge carries its provenance as a source label:
    # bench's wall-minus-floor proxy by default, the trace-measured
    # figure when the anatomy plane publishes (telemetry/anatomy.py)
    M.note_exposed_comm(0.012)
    assert reg.gauge("rlt_comm_exposed_seconds").value(
        source="wall_minus_floor") == pytest.approx(0.012)
    M.note_exposed_comm(0.008, source="anatomy")
    assert reg.gauge("rlt_comm_exposed_seconds").value(
        source="anatomy") == pytest.approx(0.008)


def test_ring_attention_registers_rotation_bytes():
    from ray_lightning_tpu.parallel.mesh import (build_device_mesh,
                                                 set_current_mesh)
    reg = telemetry.enable_metrics(pump=False)
    ring = 4
    mesh = build_device_mesh(("data", "sequence"),
                             {"data": 1, "sequence": ring},
                             devices=jax.devices()[:ring])
    try:
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(key, (2, 256, 2, 16), jnp.float32)
                   for key in ks)
        from ray_lightning_tpu.parallel.ring import ring_attention
        try:
            ring_attention(q, k, v, causal=True, dtype=jnp.float32,
                           mesh=mesh)
        except AttributeError:
            # minimal-jax CI images lack jax.shard_map; the traced cost
            # registers at call entry, before the shard_map dispatch, so
            # the accounting under test is unaffected
            pass
    finally:
        set_current_mesh(None)
    # each rotation moves global K+V once; ring-1 rotations per call
    expected = (ring - 1) * (k.size * 4 + v.size * 4)
    assert reg.traced_bytes["ring"] == expected
    M.on_step(0.01, k=2)
    assert reg.counter("rlt_collective_bytes_total").value(op="ring") \
        == 2 * expected


def test_pipeline_registers_hop_bytes():
    from jax.sharding import Mesh
    from ray_lightning_tpu.parallel.pipeline import pipeline_forward
    reg = telemetry.enable_metrics(pump=False)
    S, mb = 2, 4
    devs = np.array(jax.devices()[:S]).reshape(1, S)
    params = {
        "w": jax.random.normal(jax.random.PRNGKey(0), (8, 16, 16)) * 0.3,
        "b": jnp.zeros((8, 16)),
    }
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))
    try:
        pipeline_forward(lambda p, h: jnp.tanh(h @ p["w"] + p["b"]),
                         params, x, n_microbatches=mb,
                         mesh=Mesh(devs, ("data", "stage")))
    except AttributeError:
        pass   # jax.shard_map missing (see ring test note); the traced
        # cost registers before the dispatch
    x_bytes = x.size * 4
    expected = S * (mb + S - 1) * x_bytes // mb + x_bytes
    assert reg.traced_bytes["pipeline"] == expected > 0


def test_strategy_step_collective_bytes():
    from ray_lightning_tpu.parallel.mesh import build_device_mesh
    from ray_lightning_tpu.parallel.strategy import (DataParallelStrategy,
                                                     Zero1Strategy)
    mesh = build_device_mesh(("data",), {"data": 4},
                             devices=jax.devices()[:4])
    params = {"w": jax.ShapeDtypeStruct((16, 16), jnp.float32),
              "b": jax.ShapeDtypeStruct((16,), jnp.float32)}
    state = SimpleNamespace(params=params)
    nbytes = (16 * 16 + 16) * 4
    assert DataParallelStrategy().step_collective_bytes(mesh, state) \
        == {"grad_all_reduce": nbytes}
    assert Zero1Strategy().step_collective_bytes(mesh, state) \
        == {"grad_reduce_scatter": nbytes, "param_all_gather": nbytes}
    one = build_device_mesh(("data",), {"data": 1},
                            devices=jax.devices()[:1])
    assert Zero1Strategy().step_collective_bytes(one, state) == {}


# -- heartbeat brief (satellite: watchdog says WHAT a rank was doing) ---

def test_heartbeat_carries_metrics_brief_and_watchdog_uses_it(tmp_path):
    telemetry.enable_metrics(pump=False)
    M.on_step(0.01, step=17)
    M.record_collective("gather", 4096)
    beat = make_heartbeat(5)
    assert beat["metrics"]["step"] == 17
    assert beat["metrics"]["last_collective"] == "gather"

    clock = [0.0]
    agg = TelemetryAggregator(str(tmp_path), heartbeat_timeout=5.0,
                              clock=lambda: clock[0])
    agg.maybe_ingest(beat)
    clock[0] = 10.0
    line = agg._describe(agg.heartbeats()[beat["pid"]]["beat"], 10.0)
    assert "step 17" in line and "last collective 'gather'" in line


# -- aggregator derivations ---------------------------------------------

def _window(rank, metrics, ts=100.0):
    return M.metrics_item(rank, metrics) | {"ts": ts}


def _counter_m(name, value, **labels):
    return {"name": name, "type": "counter", "labels": labels,
            "value": value}


def test_collective_bandwidth_prefers_measured_seconds(tmp_path):
    agg = TelemetryAggregator(str(tmp_path))
    gib = 2**30
    for rank in (0, 1):
        agg.maybe_ingest(_window(rank, [
            _counter_m("rlt_collective_bytes_total", 2 * gib, op="gather"),
            _counter_m("rlt_collective_seconds_total", 1.0, op="gather"),
        ]))
    stats = agg.collective_stats()
    assert stats["gather"]["bytes"] == 4 * gib
    assert stats["gather"]["per_rank"]["0"]["gibs"] == pytest.approx(2.0)
    # ranks transfer concurrently: job bandwidth sums per-rank rates
    assert stats["gather"]["gibs"] == pytest.approx(4.0)


def test_collective_bandwidth_falls_back_to_step_time(tmp_path):
    agg = TelemetryAggregator(str(tmp_path))
    # 2 GiB of in-step (traced) collective, no measured seconds, but 4s
    # of recorded step spans -> 0.5 GiB/s lower bound
    agg.ingest_records(0, [{"t": "span", "name": "step", "ts": 100.0,
                            "dur": 4.0, "rank": 0, "depth": 0}])
    agg.maybe_ingest(_window(0, [
        _counter_m("rlt_collective_bytes_total", 2 * 2**30, op="ring")]))
    stats = agg.collective_stats()
    assert stats["ring"]["per_rank"]["0"]["gibs"] == pytest.approx(0.5)


def test_export_writes_metrics_jsonl_and_summary_fields(tmp_path, caplog):
    agg = TelemetryAggregator(str(tmp_path))
    agg.maybe_ingest(_window(0, [
        _counter_m("rlt_collective_bytes_total", 2**30, op="gather"),
        _counter_m("rlt_collective_seconds_total", 2.0, op="gather"),
        {"name": "rlt_hbm_peak_bytes", "type": "gauge",
         "labels": {"device": "0"}, "value": 12345},
        _counter_m("rlt_telemetry_dropped_total", 3),
    ]))
    with caplog.at_level(logging.WARNING,
                         logger="ray_lightning_tpu.telemetry.aggregator"):
        paths = agg.export()
    summary = paths["summary"]
    assert summary["metrics"]["collectives"]["gather"]["gibs"] == \
        pytest.approx(0.5)
    assert summary["metrics"]["hbm_peak_bytes"] == {"0": 12345}
    assert summary["hbm_peak_bytes"] == 12345
    assert summary["collective_gibs"] == pytest.approx(0.5)
    # silent data loss is surfaced: summary field + driver warning
    assert summary["metrics"]["dropped_records"] == {"0": 3}
    assert any("dropped records" in r.message for r in caplog.records)
    with open(paths["metrics"]) as f:
        lines = [json.loads(line) for line in f]
    assert lines[0]["kind"] == "metrics" and lines[0]["rank"] == 0
    assert lines[-1]["t"] == "summary"


# -- Prometheus exposition + HTTP endpoint ------------------------------

_SERIES_RE = re.compile(
    r"^[a-z_][a-z0-9_]*(\{[a-zA-Z0-9_=\",.+/ -]*\})? -?[0-9.e+-]+$")


def _assert_exposition_parses(text):
    assert text.endswith("\n")
    for line in text.rstrip("\n").splitlines():
        if line.startswith("# TYPE "):
            assert line.split()[3] in ("counter", "gauge", "histogram")
            continue
        assert _SERIES_RE.match(line), f"unparsable series line: {line!r}"


def _scraped_aggregator(tmp_path):
    agg = TelemetryAggregator(str(tmp_path))
    reg = M.MetricsRegistry(rank=0)
    reg.counter("rlt_steps_total").inc(4)
    reg.histogram("rlt_step_time_seconds").observe(0.02)
    reg.gauge("rlt_hbm_bytes").set(1024, device="0")
    reg.counter("rlt_collective_bytes_total").inc(4096, op="gather")
    agg.ingest_metrics(M.metrics_item(0, reg.snapshot()))
    agg.ingest_metrics(M.metrics_item(1, reg.snapshot()))
    return agg


def test_render_prometheus_format(tmp_path):
    text = render_prometheus(_scraped_aggregator(tmp_path))
    _assert_exposition_parses(text)
    assert '# TYPE rlt_steps_total counter' in text
    assert 'rlt_steps_total{rank="0"} 4' in text
    assert 'rlt_steps_total{rank="1"} 4' in text
    assert 'rlt_hbm_bytes{device="0",rank="0"} 1024' in text
    assert 'rlt_collective_bytes_total{op="gather",rank="0"} 4096' in text
    # histogram: cumulative buckets, +Inf terminal, sum/count series
    assert 'rlt_step_time_seconds_bucket{le="+Inf",rank="0"} 1' in text
    assert 'rlt_step_time_seconds_count{rank="0"} 1' in text


def test_http_server_serves_metrics_and_status(tmp_path):
    agg = _scraped_aggregator(tmp_path)
    server = MetricsHTTPServer(agg, port=0)
    server.start()
    try:
        with urllib.request.urlopen(server.url + "/metrics") as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            body = r.read().decode()
        _assert_exposition_parses(body)
        assert 'rlt_steps_total{rank="1"} 4' in body
        with urllib.request.urlopen(server.url + "/status") as r:
            status = json.load(r)
        assert status["ranks"]["0"]["step"] == 4
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(server.url + "/nope")
    finally:
        server.stop()


def test_status_merges_heartbeats_and_step_stats(tmp_path):
    agg = _scraped_aggregator(tmp_path)
    agg.maybe_ingest(make_heartbeat(0))
    for i in range(4):
        agg.ingest_records(0, [{"t": "span", "name": "step",
                                "ts": 100.0 + i, "dur": 0.010, "rank": 0,
                                "depth": 0}])
    status = render_status(agg)
    r0 = status["ranks"]["0"]
    assert r0["step"] == 4
    assert r0["heartbeat_age_s"] >= 0
    assert r0["step_p50_ms"] == pytest.approx(10.0)
    assert r0["step_p95_ms"] == pytest.approx(10.0)


# -- config / port resolution -------------------------------------------

def test_metrics_port_resolution(monkeypatch):
    from ray_lightning_tpu.telemetry import TelemetryConfig
    cfg = TelemetryConfig.resolve(True)
    assert cfg.metrics and cfg.resolved_metrics_port() is None
    monkeypatch.setenv("RLT_METRICS_PORT", "9100")
    assert cfg.resolved_metrics_port() == 9100
    monkeypatch.setenv("RLT_METRICS_PORT", "nope")
    assert cfg.resolved_metrics_port() is None
    assert TelemetryConfig.resolve(
        {"metrics_port": 0}).resolved_metrics_port() == 0


def test_tune_trial_gets_ephemeral_port_and_records_url(tmp_path):
    """Inside a builtin tune trial an explicit port downgrades to
    ephemeral (concurrent trials must not fight over one bind) and the
    bound URL lands on the Trial for ExperimentAnalysis."""
    from ray_lightning_tpu.telemetry import TelemetryConfig
    from ray_lightning_tpu.tune.runner import Trial
    from ray_lightning_tpu.tune.session import TrialSession, set_session
    from ray_lightning_tpu.telemetry.exporter import start_metrics_server
    trial = Trial("trial_00000", {}, str(tmp_path / "trial_00000"))
    set_session(TrialSession(trial, lambda *a: None))
    try:
        cfg = TelemetryConfig.resolve({"metrics_port": 9100})
        server = start_metrics_server(
            _scraped_aggregator(tmp_path), cfg)
        assert server is not None
        try:
            assert server.port != 9100
            assert trial.metrics_url == server.url
        finally:
            server.stop()
    finally:
        set_session(None)


# -- trainer integration (in-process) -----------------------------------

def test_local_fit_exports_metrics_jsonl(tmp_path, seed):
    trainer = Trainer(max_epochs=1, limit_train_batches=4,
                      limit_val_batches=0, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      log_every_n_steps=1, default_root_dir=str(tmp_path),
                      telemetry={"metrics_interval": 0.1,
                                 "metrics_port": 0})
    trainer.fit(BoringModel())
    paths = trainer._telemetry_paths
    assert paths["metrics_url"].startswith("http://127.0.0.1:")
    with open(paths["metrics"]) as f:
        lines = [json.loads(line) for line in f]
    final = {}
    for m in lines[-2]["metrics"]:      # last window before the summary
        final[(m["name"], tuple(sorted(m["labels"].items())))] = m
    assert final[("rlt_steps_total", ())]["value"] == 4
    assert final[("rlt_compiles_total", ())]["value"] == 1
    hist = final[("rlt_step_time_seconds", ())]
    assert hist["count"] == 4
    assert ("rlt_hbm_bytes", (("device", "0"),)) in final
    assert final[("rlt_data_wait_seconds_total", ())]["value"] > 0
    # registry torn down after the run
    assert not M.metrics_enabled()


def test_metrics_disabled_leaves_no_stream(tmp_path, seed):
    trainer = Trainer(max_epochs=1, limit_train_batches=2,
                      limit_val_batches=0, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      default_root_dir=str(tmp_path),
                      telemetry={"metrics": False})
    trainer.fit(BoringModel())
    paths = trainer._telemetry_paths
    assert "metrics" not in paths and "metrics_url" not in paths


# -- end-to-end over the cluster backend --------------------------------

@pytest.mark.slow
def test_e2e_two_workers_collective_bytes_and_live_scrape(tmp_path, seed):
    """2-worker ZeRO-1 fit: per-rank metrics windows reach the driver,
    /metrics is scrapable WHILE the run is live, and the summary carries
    size-consistent per-op collective bytes + achieved GiB/s."""
    plugin = cpu_plugin(2, strategy="zero1")
    scrape = {}

    def scraper():
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            server = getattr(plugin, "_metrics_server", None)
            if server is None:
                time.sleep(0.05)
                continue
            try:
                with urllib.request.urlopen(server.url + "/metrics",
                                            timeout=2) as r:
                    body = r.read().decode()
                with urllib.request.urlopen(server.url + "/status",
                                            timeout=2) as r:
                    status = json.load(r)
            except Exception:
                time.sleep(0.1)
                continue
            if 'rlt_steps_total{rank="0"}' in body \
                    and 'rlt_steps_total{rank="1"}' in body:
                scrape["metrics"] = body
                scrape["status"] = status
                return
            time.sleep(0.1)

    t = threading.Thread(target=scraper, daemon=True)
    t.start()
    module = BoringModel()
    trainer = Trainer(max_epochs=1, limit_train_batches=4,
                      limit_val_batches=0, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      log_every_n_steps=1, plugins=[plugin],
                      default_root_dir=str(tmp_path),
                      telemetry={"heartbeat_interval": 0.5,
                                 "metrics_interval": 0.2,
                                 "metrics_port": 0})
    trainer.fit(module)
    t.join(timeout=10)

    # -- live scrape landed while workers were still fitting
    assert "metrics" in scrape, "never scraped both ranks live"
    _assert_exposition_parses(scrape["metrics"])
    assert "# TYPE rlt_step_time_seconds histogram" in scrape["metrics"]
    assert 'rlt_hbm_bytes{device="0",rank="1"}' in scrape["metrics"]
    assert "rlt_collective_bytes_total" in scrape["metrics"]
    assert set(scrape["status"]["ranks"]) == {"0", "1"}

    # -- exported window stream + per-op bandwidth summary
    paths = trainer._telemetry_paths
    with open(paths["metrics"]) as f:
        windows = [json.loads(line) for line in f][:-1]
    assert {w["rank"] for w in windows} == {0, 1}
    summary = paths["summary"]["metrics"]
    collectives = summary["collectives"]
    params_bytes = sum(
        np.asarray(leaf).nbytes for leaf in jax.tree_util.tree_leaves(
            module._trained_variables["params"]))
    # gather: _finalize_fit fetches the params tree once per rank, and
    # both ranks report the identical global payload
    per_rank = collectives["gather"]["per_rank"]
    assert per_rank["0"]["bytes"] == per_rank["1"]["bytes"] == params_bytes
    assert per_rank["0"]["gibs"] > 0
    # ZeRO in-step traffic: one params' worth per op per executed step
    for op in ("grad_reduce_scatter", "param_all_gather"):
        rank_bytes = collectives[op]["per_rank"]["0"]["bytes"]
        assert rank_bytes == 4 * params_bytes, op
        assert collectives[op]["gibs"] > 0
    assert paths["summary"]["collective_gibs"] > 0
    assert "hbm_peak_bytes" in paths["summary"]
