"""Behavioral distributed-plugin coverage mirroring the rest of the
reference's test pyramid: driver-without-accelerator isolation
(DelayedGPUAccelerator parity, util.py:11-37 / ray_ddp.py:188-204),
per-stage distributed-sampler wiring asserted from inside workers
(test_ddp.py:177-209), EarlyStopping under actors (test_ddp.py:287-306),
and finetuning from a distributed checkpoint with a plain local trainer
(test_ddp_sharded.py:67-105)."""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np

from ray_lightning_tpu import (
    Callback,
    EarlyStopping,
    RayXlaPlugin,
    Trainer,
)
from ray_lightning_tpu.models import BoringModel

from tests.utils import cpu_plugin, train_test


def test_driver_needs_no_accelerator(tmp_path):
    """The driver must never initialize a JAX backend during a
    distributed fit — the DelayedTPUAccelerator property (reference:
    CPU-only driver + DelayedGPUAccelerator, util.py:11-37).  Enforced by
    giving the driver process a platform that cannot initialize: any
    driver-side backend touch would raise."""
    script = textwrap.dedent("""
        from ray_lightning_tpu import Trainer
        from ray_lightning_tpu.plugins import RayXlaPlugin
        from ray_lightning_tpu.models import BoringModel

        plugin = RayXlaPlugin(num_workers=2, platform="cpu")
        trainer = Trainer(plugins=[plugin], max_epochs=1,
                          limit_train_batches=2, limit_val_batches=1,
                          num_sanity_val_steps=0,
                          enable_checkpointing=False, seed=0)
        model = BoringModel()
        trainer.fit(model)
        assert model._trained_variables is not None
        print("DRIVER_OK")
    """)
    env = dict(os.environ)
    # a platform name that cannot init: any driver-side jax.devices()/
    # jit would fail loudly.  Workers override via the plugin's env
    # plumbing (JAX_PLATFORMS=cpu).
    env["JAX_PLATFORMS"] = "no_such_platform"
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, timeout=300,
                          env=env, cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stderr
    assert "DRIVER_OK" in proc.stdout


def test_distributed_sampler_shards_are_disjoint(tmp_path, seed):
    """Each worker must see a distinct shard of the training data
    (DistributedSampler wiring parity, test_ddp.py:177-209).  The
    recorder is defined in-function so cloudpickle ships it by value —
    the assertion-via-callback idiom (test_ddp.py:184-204)."""

    class ShardRecorder(Callback):
        def __init__(self, out_dir: str):
            self.out_dir = out_dir
            self.seen: list = []

        def on_train_batch_end(self, trainer, module, outputs, batch,
                               batch_idx):
            x = batch[0] if isinstance(batch, (tuple, list)) else batch
            # BoringModel rows are distinguishable by their first column
            self.seen.extend(np.asarray(x)[:, 0].tolist())

        def on_train_end(self, trainer, module):
            path = os.path.join(self.out_dir,
                                f"shard_rank{trainer.global_rank}.json")
            with open(path, "w") as f:
                json.dump({"world_size": trainer.world_size,
                           "rank": trainer.global_rank,
                           "seen": self.seen}, f)

    trainer = Trainer(
        plugins=[cpu_plugin(2)], max_epochs=1,
        limit_val_batches=0, num_sanity_val_steps=0,
        enable_checkpointing=False, seed=0,
        callbacks=[ShardRecorder(str(tmp_path))])
    trainer.fit(BoringModel(dataset_length=16, batch_size=4))

    shards = []
    for rank in range(2):
        with open(tmp_path / f"shard_rank{rank}.json") as f:
            rec = json.load(f)
        assert rec["world_size"] == 2
        shards.append(set(rec["seen"]))
    assert shards[0] and shards[1]
    assert shards[0].isdisjoint(shards[1])


def test_early_stopping_under_actors(tmp_path, seed):
    """EarlyStopping inside workers stops the fit before max_epochs, and
    the epoch count round-trips to the driver (test_ddp.py:287-306)."""

    class PlateauModel(BoringModel):
        # lr=0 freezes weights → flat val loss → patience trips
        def __init__(self):
            super().__init__(lr=0.0)

    trainer = Trainer(
        plugins=[cpu_plugin(2)], max_epochs=10,
        limit_train_batches=2, limit_val_batches=1,
        num_sanity_val_steps=0, enable_checkpointing=False, seed=0,
        callbacks=[EarlyStopping(monitor="val_loss", patience=1,
                                 min_delta=1e-9)])
    trainer.fit(PlateauModel())
    # flat metric: first epoch sets best, epoch 2 trips patience=1
    assert trainer.current_epoch < 10


def test_finetune_from_distributed_checkpoint(tmp_path, seed):
    """A checkpoint written by a distributed fit must load into a plain
    local Trainer for finetuning/resume (test_ddp_sharded.py:67-105)."""
    root = tmp_path / "dist"
    trainer = Trainer(
        plugins=[cpu_plugin(2)], max_epochs=1,
        limit_train_batches=4, limit_val_batches=1,
        num_sanity_val_steps=0, seed=0,
        default_root_dir=str(root))
    model = BoringModel()
    trainer.fit(model)
    best = trainer.checkpoint_callback.best_model_path
    assert best and os.path.exists(best)

    # finetune locally from the distributed checkpoint
    local = Trainer(max_epochs=2, limit_train_batches=4,
                    limit_val_batches=1, num_sanity_val_steps=0,
                    enable_checkpointing=False, seed=0,
                    resume_from_checkpoint=best,
                    default_root_dir=str(tmp_path / "local"))
    model2 = BoringModel()
    local.fit(model2)
    assert local.current_epoch == 2
    assert model2._trained_variables is not None

    # and evaluation-without-fit consumes the same checkpoint
    evaluator = Trainer(limit_test_batches=2, enable_checkpointing=False,
                        num_sanity_val_steps=0, seed=0,
                        default_root_dir=str(tmp_path / "eval"))
    results = evaluator.test(BoringModel(), ckpt_path=best)
    assert results


def test_weights_round_trip_differs_from_init(tmp_path, seed):
    """Driver-side weights after a distributed fit differ from the
    freshly initialized ones (train_test norm-delta assertion,
    tests/utils.py, applied across the actor boundary)."""
    trainer = Trainer(plugins=[cpu_plugin(2)], max_epochs=1,
                      limit_train_batches=8, limit_val_batches=0,
                      num_sanity_val_steps=0, enable_checkpointing=False,
                      seed=0, default_root_dir=str(tmp_path))
    train_test(trainer, BoringModel())
