"""Native (C++) input-pipeline runtime tests: bit-exact parity with the
pure-Python DataLoader path, prefetch-ring lifecycle, and graceful
fallback when disabled — the same degrade-without-the-dependency shape
the reference CI checks for Tune (test.yaml:196-226)."""

import numpy as np
import pytest

from ray_lightning_tpu import native
from ray_lightning_tpu.core.data import ArrayDataset, DataLoader


@pytest.fixture(scope="module")
def lib():
    lib = native.load_library()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    return lib


def test_gather_matches_numpy(lib):
    rng = np.random.default_rng(0)
    src = rng.standard_normal((512, 33)).astype(np.float32)
    idx = rng.integers(0, 512, size=300)
    np.testing.assert_array_equal(native.gather(src, idx), src[idx])


def test_gather_large_multithreaded(lib):
    rng = np.random.default_rng(1)
    src = rng.standard_normal((4096, 512)).astype(np.float32)  # >1MB batches
    idx = rng.permutation(4096)
    np.testing.assert_array_equal(
        native.gather(src, idx, n_threads=4), src[idx])


def test_gather_int_and_3d(lib):
    rng = np.random.default_rng(2)
    src = rng.integers(0, 100, size=(64, 4, 7)).astype(np.int64)
    idx = np.array([3, 3, 0, 63])
    np.testing.assert_array_equal(native.gather(src, idx), src[idx])


def _collect(loader):
    return [tuple(np.array(b) for b in batch) for batch in loader]


def _loaders(n=37, batch=8, **kw):
    x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
    y = np.arange(n, dtype=np.int32)
    ds = ArrayDataset(x, y)
    return (DataLoader(ds, batch_size=batch, prefetch=True, **kw),
            DataLoader(ds, batch_size=batch, prefetch=False, **kw))


@pytest.mark.parametrize("kw", [
    {},                                        # partial last batch
    {"drop_last": True},
    {"shuffle": True, "seed": 7},
    {"num_shards": 2, "shard_index": 1},
    {"shuffle": True, "num_shards": 2, "shard_index": 0},
])
def test_loader_parity(lib, kw):
    fast, slow = _loaders(**kw)
    got, want = _collect(fast), _collect(slow)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        for a, b in zip(g, w):
            np.testing.assert_array_equal(a, b)


def test_loader_parity_across_epochs(lib):
    fast, slow = _loaders(shuffle=True)
    for epoch in range(3):
        fast.set_epoch(epoch)
        slow.set_epoch(epoch)
        got, want = _collect(fast), _collect(slow)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g[0], w[0])


def test_dict_dataset(lib):
    ds = ArrayDataset(a=np.arange(20, dtype=np.float32),
                      b=np.arange(20, dtype=np.int64) * 2)
    loader = DataLoader(ds, batch_size=6, prefetch=True)
    batches = list(loader)
    assert set(batches[0].keys()) == {"a", "b"}
    np.testing.assert_array_equal(np.array(batches[-1]["a"]),
                                  np.array([18.0, 19.0], dtype=np.float32))


def test_early_exit_does_not_hang(lib):
    fast, _ = _loaders(n=1000, batch=4)
    it = iter(fast)
    next(it)
    next(it)
    it.close()  # abort mid-epoch; prefetcher must stop cleanly
    # a fresh epoch over the same loader still works
    assert len(_collect(fast)) == len(fast)


def test_prefetcher_batches_are_owned(lib):
    """Yielded batches transfer ownership: every retained batch stays
    intact through the whole epoch (no ring-slot recycling visible to the
    consumer), matching the Python path's fresh-copy semantics."""
    n = 64
    x = np.arange(n, dtype=np.int64)
    pf = native.NativePrefetcher([x], batch_size=4, queue_depth=2)
    retained = [buf for (buf,) in pf.iter_epoch(np.arange(n))]
    for k, buf in enumerate(retained):
        np.testing.assert_array_equal(np.array(buf), x[k * 4:(k + 1) * 4])
    pf.close()


def test_prefetcher_clamps_queue_depth(lib):
    """depth<2 would let a stale ready-flag serve batch k's data as
    batch k+1; the wrapper clamps it."""
    pf = native.NativePrefetcher([np.arange(8, dtype=np.int64)],
                                 batch_size=2, queue_depth=1)
    assert pf.queue_depth == 2
    batches = [np.array(b) for (b,) in pf.iter_epoch(np.arange(8))]
    np.testing.assert_array_equal(np.concatenate(batches), np.arange(8))
    pf.close()


def test_loader_batches_retained_across_epoch(lib):
    """list(loader) snapshots must be correct even without copying —
    the regression mode of slot-view recycling."""
    n, batch = 40, 4
    x = np.arange(n * 2, dtype=np.float32).reshape(n, 2)
    loader = DataLoader(ArrayDataset(x), batch_size=batch, prefetch=True)
    batches = list(loader)
    for k, b in enumerate(batches):
        np.testing.assert_array_equal(b, x[k * batch:(k + 1) * batch])


def test_non_contiguous_falls_back(lib):
    """Transposed leaves must take the Python path (no hidden per-epoch
    dataset copies) and still yield correct batches."""
    x = np.arange(12, dtype=np.float32).reshape(3, 4).T  # (4,3) F-order
    loader = DataLoader(ArrayDataset(x), batch_size=2, prefetch=True)
    got = np.concatenate(list(loader))
    np.testing.assert_array_equal(got, np.ascontiguousarray(x))


def test_malformed_thread_env(lib, monkeypatch):
    monkeypatch.setenv("RLT_NATIVE_THREADS", "auto")
    assert native.default_threads() >= 1


def test_disabled_via_env(lib, monkeypatch):
    monkeypatch.setenv("RLT_NATIVE", "0")
    assert native.load_library() is None
    fast, slow = _loaders()
    # loader silently falls back; parity still holds
    for g, w in zip(_collect(fast), _collect(slow)):
        np.testing.assert_array_equal(g[0], w[0])


def test_trainer_end_to_end_with_native_loader(lib, tmp_path, seed):
    """Full fit through the native input pipeline."""
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.models import BoringModel

    trainer = Trainer(max_epochs=1, limit_train_batches=4,
                      limit_val_batches=2, num_sanity_val_steps=0,
                      enable_checkpointing=False,
                      default_root_dir=str(tmp_path))
    trainer.fit(BoringModel())
    assert "loss" in trainer.callback_metrics
