"""Mixture-of-Experts layer + expert-parallel training (ops/moe.py).

Beyond the reference's parity surface (SURVEY.md §2.3: EP absent there),
so these tests have no reference analog; they follow the repo's own
pattern — numeric equivalence against a dense oracle, then an
end-to-end distributed run on the 8-virtual-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_lightning_tpu.ops.moe import MoEMLP, total_aux_loss


def _apply(layer, x, rng=0):
    variables = layer.init(jax.random.PRNGKey(rng), x)
    out, state = layer.apply(variables, x, mutable=["losses"])
    return variables, out, state


def test_single_expert_matches_dense_ffn():
    """n_experts=1, top_k=1, capacity=S: routing is the identity, so the
    layer must equal a plain gelu FFN with the same weights."""
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16),
                          dtype=jnp.float32)
    layer = MoEMLP(n_experts=1, d_ff=32, top_k=1, capacity_factor=1.0,
                   dtype=jnp.float32)
    variables, out, _ = _apply(layer, x)
    w1 = variables["params"]["w1"][0]
    w2 = variables["params"]["w2"][0]
    dense = jax.nn.gelu(x @ w1) @ w2
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               rtol=1e-5, atol=1e-5)


def test_top2_combine_weights_sum_to_one():
    """With capacity ≥ k·S no token is dropped, so each token's combine
    weights over (expert, slot) sum to 1 after renormalization."""
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 8, 16))
    layer = MoEMLP(n_experts=4, d_ff=32, top_k=2, capacity_factor=4.0)

    # reach inside: rebuild combine by re-running apply with capture
    # (cheaper: check output is a convex combination by linearity —
    # constant input rows must map to a constant output row)
    const = jnp.ones((1, 8, 16))
    _, out, _ = _apply(layer, const)
    # all tokens identical → all routed identically → identical outputs
    np.testing.assert_allclose(np.asarray(out[0, 0]), np.asarray(out[0, -1]),
                               rtol=1e-4, atol=1e-4)


def test_capacity_overflow_drops_tokens():
    """capacity_factor→0 forces capacity=1 slot per expert: most tokens
    overflow and must come out exactly zero (residual path territory)."""
    x = jax.random.normal(jax.random.PRNGKey(3), (1, 16, 8))
    layer = MoEMLP(n_experts=2, d_ff=16, top_k=1, capacity_factor=0.01)
    _, out, _ = _apply(layer, x)
    row_norms = np.linalg.norm(np.asarray(out[0]), axis=-1)
    # ≤ 2 experts × 1 slot survive; the rest are dropped → zero rows
    assert (row_norms < 1e-6).sum() >= 16 - 2


def test_aux_loss_sown_and_bounded():
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 32, 16))
    layer = MoEMLP(n_experts=4, d_ff=32, top_k=2)
    _, _, state = _apply(layer, x)
    aux = total_aux_loss(state)
    assert aux is not None
    aux = float(aux)
    # Switch load-balance loss: 1.0 at perfect balance, ≥ prob-mass lower
    # bound always; collapse onto one expert gives ~n_experts
    assert 0.5 <= aux <= 4.0 + 1e-3
    assert np.isfinite(aux)


def test_total_aux_loss_none_for_dense_models():
    assert total_aux_loss({}) is None
    assert total_aux_loss(None) is None


def test_grads_flow_to_all_expert_params():
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 16, 8))
    layer = MoEMLP(n_experts=2, d_ff=16, top_k=2, capacity_factor=2.0)
    variables = layer.init(jax.random.PRNGKey(0), x)

    def loss_fn(params):
        out, state = layer.apply({"params": params}, x, mutable=["losses"])
        return jnp.sum(out ** 2) + total_aux_loss(state)

    grads = jax.jit(jax.grad(loss_fn))(variables["params"])
    for path, leaf in jax.tree_util.tree_leaves_with_path(grads):
        assert np.isfinite(np.asarray(leaf)).all(), path
    # router must receive signal (through combine weights and aux loss)
    assert float(jnp.abs(grads["router"]).sum()) > 0


def test_top1_router_gets_task_gradient():
    """Switch-style top-1 scales expert output by the RAW gate prob; a
    renormalized (constant-1.0) combine weight would leave the router
    trainable only through the tiny aux loss."""
    x = jax.random.normal(jax.random.PRNGKey(6), (2, 16, 8))
    layer = MoEMLP(n_experts=4, d_ff=16, top_k=1, capacity_factor=2.0)
    variables = layer.init(jax.random.PRNGKey(0), x)

    def task_loss(params):  # no aux term: isolate the task-loss path
        out, _ = layer.apply({"params": params}, x, mutable=["losses"])
        return jnp.sum(out ** 2)

    g = jax.grad(task_loss)(variables["params"])["router"]
    assert float(jnp.abs(g).sum()) > 1e-3


def test_moe_gpt_trains_on_expert_mesh(seed):
    """End-to-end: moe-tiny GPT on a (data=2, expert=2, tensor=2) mesh.
    Expert weights must actually shard on the expert axis, training must
    run and produce finite decreasing loss, and the aux metric must
    surface in callback_metrics."""
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.models.gpt import (GPTLightningModule,
                                              gpt_partition_rules)
    from ray_lightning_tpu.parallel.strategy import SpmdStrategy

    module = GPTLightningModule("moe-tiny", dataset_size=64, batch_size=8,
                                lr=1e-2)
    strategy = SpmdStrategy(
        rules=gpt_partition_rules(),
        axis_names=("data", "expert", "tensor"),
        axis_sizes={"expert": 2, "tensor": 2},
    )
    trainer = Trainer(max_epochs=1, max_steps=8, strategy=strategy,
                      enable_checkpointing=False, num_sanity_val_steps=0,
                      limit_val_batches=0, log_every_n_steps=1)
    trainer.fit(module)

    assert trainer.global_step == 8
    loss = float(trainer.callback_metrics["loss"])
    assert np.isfinite(loss)
    assert "moe_aux" in trainer.callback_metrics
    aux = float(trainer.callback_metrics["moe_aux"])
    assert 0.5 <= aux <= 8.0

    # verify expert sharding actually happened on the expert axis
    w1 = trainer.state.params["h1"]["moe"]["w1"]
    spec = w1.sharding.spec
    assert spec[0] == "expert", f"expected expert-sharded w1, got {spec}"


def test_moe_gpt_loss_decreases(seed):
    """Learnability: a few steps on the structured synthetic LM dataset
    must reduce the loss (routing + aux loss must not break learning)."""
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.core.callbacks import Callback
    from ray_lightning_tpu.models.gpt import GPTLightningModule

    module = GPTLightningModule("moe-tiny", dataset_size=128, batch_size=8,
                                lr=1e-2)

    losses = []

    class Track(Callback):
        def on_train_batch_end(self, trainer, mod, metrics, batch, idx):
            losses.append(float(np.asarray(metrics["loss"])))

    trainer = Trainer(max_epochs=2, enable_checkpointing=False,
                      num_sanity_val_steps=0, limit_val_batches=0,
                      callbacks=[Track()], log_every_n_steps=1)
    trainer.fit(module)
    assert np.mean(losses[-4:]) < np.mean(losses[:4]) - 0.3, losses


def test_moe_checkpoint_roundtrip(seed, tmp_path):
    """MoE state (incl. the sown losses collection) must survive the
    save→restore cycle and resume cleanly."""
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.models.gpt import GPTLightningModule

    module = GPTLightningModule("moe-tiny", dataset_size=32, batch_size=8)
    trainer = Trainer(max_epochs=1, max_steps=2, enable_checkpointing=False,
                      num_sanity_val_steps=0, limit_val_batches=0,
                      log_every_n_steps=1)
    trainer.fit(module)
    path = str(tmp_path / "moe.ckpt")
    trainer.save_checkpoint(path)

    module2 = GPTLightningModule("moe-tiny", dataset_size=32, batch_size=8)
    trainer2 = Trainer(max_epochs=2, enable_checkpointing=False,
                       num_sanity_val_steps=0, limit_val_batches=0,
                       log_every_n_steps=1, resume_from_checkpoint=path)
    trainer2.fit(module2)
    assert trainer2.global_step > 2
    assert np.isfinite(float(trainer2.callback_metrics["loss"]))


def _two_step_losses(policy_name, monkeypatch):
    """Two train steps of a remat-enabled moe-tiny under the named
    policy.  TWO steps on purpose: step 2's loss depends on step 1's
    UPDATE, so wrong cotangents from a broken saved-vs-recomputed
    residual show up here — a single forward-pass loss would match even
    with corrupted gradients."""
    import optax

    from ray_lightning_tpu.core.steps import build_init_fn, build_train_step
    from ray_lightning_tpu.models.gpt import GPTLightningModule

    monkeypatch.setenv("RLT_REMAT_POLICY", policy_name)
    module = GPTLightningModule("moe-tiny", dataset_size=8, batch_size=4)
    # moe-tiny has remat=False; flip it on so the policy engages
    import dataclasses
    module.config = dataclasses.replace(module.config, remat=True)
    module.setup_model()
    tx = optax.sgd(0.1)
    batch = jax.tree_util.tree_map(
        np.asarray, next(iter(module.train_dataloader())))
    state = jax.jit(build_init_fn(module, tx))(jax.random.PRNGKey(0), batch)
    step = jax.jit(build_train_step(module, tx))
    losses = []
    for _ in range(2):
        state, metrics = step(state, batch)
        losses.append(float(np.asarray(metrics["loss"])))
    return losses


@pytest.fixture(scope="module")
def dots_two_step_losses():
    """Baseline leg shared across the parametrized policies (one
    build+compile instead of one per policy)."""
    mp = pytest.MonkeyPatch()
    try:
        return _two_step_losses("dots", mp)
    finally:
        mp.undo()


@pytest.mark.parametrize("policy", ["dots_moe_act", "dots_moe"])
def test_moe_save_list_policies_run_and_match(policy, monkeypatch,
                                              dots_two_step_losses):
    """The named-save policies (ops/moe.py checkpoint_names composed via
    save_only_these_names, models/gpt.py _remat_policy) are documented
    rejected options — measured slower than plain dots on the v5e — but
    they must stay BUILDABLE and numerically identical to dots: remat
    policies change what is saved vs recomputed, never math (including
    the backward — see _two_step_losses).  Guards the checkpoint_name
    tags and the policy composition against jax API drift."""
    got = _two_step_losses(policy, monkeypatch)
    assert np.all(np.isfinite(got))
    np.testing.assert_allclose(got, dots_two_step_losses, rtol=1e-6,
                               err_msg=f"{policy} changed training math")
