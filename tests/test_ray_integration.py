"""Real-Ray integration suite (VERDICT round-1 missing #2/#3).

Skipped entirely unless a genuine Ray runtime is importable — the
ray-integration CI job installs ``ray[tune]`` and runs this file plus
the distributed suite under ``RLT_BACKEND=ray``.  Everything here
executes against REAL Ray: real actors, real ``ray.util.queue``, real
``runtime_env`` propagation, and (for the Tune tests) genuine
``ray.tune.run`` trials — the parts the stub tests in
test_ray_backend.py / test_ray_tune_bridge.py can only shape-check.
Reference analogs: tests/test_ddp.py:19-38 fixtures,
tests/test_tune.py, tests/test_client.py:10-14.
"""

import os

import pytest

ray = pytest.importorskip("ray")

from ray_lightning_tpu import RayXlaPlugin, Trainer  # noqa: E402
from ray_lightning_tpu import tune as rlt_tune  # noqa: E402
from ray_lightning_tpu.models import BoringModel  # noqa: E402


@pytest.fixture
def ray_backend_env(monkeypatch):
    """A fresh local Ray runtime with the framework pinned to it."""
    monkeypatch.setenv("RLT_BACKEND", "ray")
    from ray_lightning_tpu.cluster import backend as backend_mod
    backend_mod.set_backend(None)
    if not ray.is_initialized():
        ray.init(num_cpus=4, include_dashboard=False,
                 ignore_reinit_error=True)
    yield
    backend = backend_mod._backend
    if backend is not None:
        backend.shutdown()
    backend_mod.set_backend(None)
    ray.shutdown()


def _tune_run(ray_tune, train_fn, **kwargs):
    """ray.tune.run across Ray versions: the results-dir kwarg was
    renamed local_dir → storage_path and eventually dropped; the
    default (~/ray_results) is fine for CI, so just call without it and
    tolerate signature drift on verbose."""
    try:
        return ray_tune.run(train_fn, **kwargs)
    except TypeError:
        kwargs.pop("verbose", None)
        return ray_tune.run(train_fn, **kwargs)


def _fit(n_workers=2, callbacks=()):
    module = BoringModel()
    trainer = Trainer(
        max_epochs=2, limit_train_batches=4, limit_val_batches=2,
        num_sanity_val_steps=0, enable_checkpointing=False,
        callbacks=list(callbacks),
        plugins=[RayXlaPlugin(num_workers=n_workers, platform="cpu")],
    )
    trainer.fit(module)
    return trainer, module


def test_train_over_real_ray_actors(ray_backend_env, seed):
    """End-to-end fit across 2 genuine Ray actors: runtime_env env-var
    propagation, object-store payload fan-out, queue relay, weight
    round-trip (the reference's core topology, test_ddp.py analog)."""
    import numpy as np
    trainer, module = _fit(n_workers=2)
    assert "val_loss" in trainer.callback_metrics
    vars_ = module._trained_variables
    norm = sum(float(np.abs(np.asarray(v)).sum())
               for v in _leaves(vars_["params"]))
    assert norm > 0


def _leaves(tree):
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _leaves(v)
    else:
        yield tree


def test_tune_report_in_real_ray_tune_trial(ray_backend_env, seed):
    """TuneReportCheckpointCallback fires inside a genuine ray.tune.run
    trial and the trial records metric + checkpoint (the done-bar for
    VERDICT item 1; reference tune.py:130-134, :161-178)."""
    from ray import tune as ray_tune

    def train_fn(config):
        module = BoringModel(lr=config["lr"])
        trainer = Trainer(
            max_epochs=2, limit_train_batches=4, limit_val_batches=2,
            num_sanity_val_steps=0, enable_checkpointing=False,
            callbacks=[rlt_tune.TuneReportCheckpointCallback(
                on="validation_end")],
        )
        trainer.fit(module)

    analysis = _tune_run(
        ray_tune, train_fn,
        config={"lr": 0.05},
        num_samples=1,
        resources_per_trial=rlt_tune.get_tune_resources(
            num_workers=1).as_placement_group_factory(),
        verbose=0,
    )
    (trial,) = analysis.trials
    assert trial.status == "TERMINATED"
    assert "val_loss" in trial.last_result
    assert trial.last_result["training_iteration"] == 2
    assert trial.checkpoint is not None


def test_tune_grandchild_relay_in_real_trial(ray_backend_env, seed):
    """The §3.3 topology with everything real: a genuine Tune trial
    whose training runs in grandchild Ray actors; the report rides
    ray.util.queue to the trial driver where the real session lives."""
    from ray import tune as ray_tune

    def train_fn(config):
        module = BoringModel(lr=config["lr"])
        trainer = Trainer(
            max_epochs=1, limit_train_batches=2, limit_val_batches=1,
            num_sanity_val_steps=0, enable_checkpointing=False,
            callbacks=[rlt_tune.TuneReportCallback(on="validation_end")],
            plugins=[RayXlaPlugin(num_workers=2, platform="cpu")],
        )
        trainer.fit(module)

    analysis = _tune_run(
        ray_tune, train_fn,
        config={"lr": 0.05},
        num_samples=1,
        resources_per_trial=rlt_tune.get_tune_resources(
            num_workers=2).as_placement_group_factory(),
        verbose=0,
    )
    (trial,) = analysis.trials
    assert trial.status == "TERMINATED"
    assert "val_loss" in trial.last_result


@pytest.mark.skipif(
    os.environ.get("RLT_RAY_CLIENT_SMOKE") != "1",
    reason="needs a running ray head with a client server "
           "(CI sets RLT_RAY_CLIENT_SMOKE=1 + RAY_ADDRESS=ray://...)")
def test_ray_client_driving(monkeypatch, seed):
    """Driver connects over Ray Client (pickle-on-gRPC) and trains on
    the cluster — the reference's tests/test_client.py path.  The CI job
    starts ``ray start --head`` and exports RAY_ADDRESS=ray://127.0.0.1:
    10001 before running this test."""
    assert os.environ.get("RAY_ADDRESS", "").startswith("ray://")
    monkeypatch.setenv("RLT_BACKEND", "ray")
    from ray_lightning_tpu.cluster import backend as backend_mod
    backend_mod.set_backend(None)
    try:
        trainer, module = _fit(n_workers=2)
        assert "val_loss" in trainer.callback_metrics
    finally:
        backend = backend_mod._backend
        if backend is not None:
            backend.shutdown()
        backend_mod.set_backend(None)
        ray.shutdown()
