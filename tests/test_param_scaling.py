"""Structural validation of the big configs (BASELINE #5 class) without
allocating them: ``jax.eval_shape`` traces init, so the 1.3B-parameter
tree exists only as shapes.

Guards two regressions CPU-scale tests cannot see: the flagship config
drifting away from its parameter-count class, and new large parameters
silently falling through the partition rules to full replication
(which turns into an HBM OOM only on real hardware).
"""

import math

import jax
import jax.numpy as jnp

from ray_lightning_tpu.models.gpt import (CONFIGS, GPT,
                                          gpt_partition_rules)
from ray_lightning_tpu.parallel.strategy import SpmdStrategy, _path_str


def _abstract_params(cfg, batch=2):
    model = GPT(cfg)
    tokens = jax.ShapeDtypeStruct((batch, cfg.block_size), jnp.int32)
    variables = jax.eval_shape(model.init, jax.random.PRNGKey(0), tokens)
    return variables["params"]


def _param_count(params):
    return sum(math.prod(l.shape)
               for l in jax.tree_util.tree_leaves(params))


def test_gpt2_1p3b_is_actually_1p3b():
    n = _param_count(_abstract_params(CONFIGS["gpt2-1p3b"]))
    assert 1.2e9 < n < 1.5e9, f"{n/1e9:.2f}B params"


def test_gpt2_small_is_actually_124m():
    n = _param_count(_abstract_params(CONFIGS["gpt2-small"]))
    assert 1.1e8 < n < 1.4e8, f"{n/1e6:.0f}M params"


def _assert_large_leaves_sharded(cfg, min_elements=10**6):
    """Every ≥1M-element leaf must shard on SOME mesh axis under the
    standard (data, fsdp, tensor) rules — replicated multi-MB params on
    every chip are the silent pod-scale OOM."""
    params = _abstract_params(cfg)
    strategy = SpmdStrategy(rules=gpt_partition_rules(),
                            axis_names=("data", "fsdp", "tensor"),
                            axis_sizes={"fsdp": 2, "tensor": 2})
    mesh = strategy.build_mesh()
    flat = jax.tree_util.tree_leaves_with_path(params)
    checked = 0
    for path, leaf in flat:
        if math.prod(leaf.shape) < min_elements:
            continue
        path_str = _path_str(path)
        spec = strategy.param_spec(mesh, path_str, leaf)
        assert any(e is not None for e in spec), (
            f"{path_str} {leaf.shape} would replicate on every chip")
        checked += 1
    assert checked > 0


def test_all_large_1p3b_params_have_sharding_rules():
    _assert_large_leaves_sharded(CONFIGS["gpt2-1p3b"])


def test_all_large_bert_large_params_have_sharding_rules():
    from ray_lightning_tpu.models.bert import (CONFIGS as BERT_CONFIGS,
                                               BertForMaskedLM,
                                               bert_partition_rules)

    cfg = BERT_CONFIGS["bert-large"]
    model = BertForMaskedLM(cfg)
    tokens = jax.ShapeDtypeStruct((2, cfg.max_len), jnp.int32)
    params = jax.eval_shape(model.init, jax.random.PRNGKey(0),
                            tokens)["params"]
    strategy = SpmdStrategy(rules=bert_partition_rules(),
                            axis_names=("data", "fsdp", "tensor"),
                            axis_sizes={"fsdp": 2, "tensor": 2})
    mesh = strategy.build_mesh()
    checked = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        if math.prod(leaf.shape) < 10**6:
            continue
        path_str = _path_str(path)
        spec = strategy.param_spec(mesh, path_str, leaf)
        assert any(e is not None for e in spec), (
            f"{path_str} {leaf.shape} would replicate on every chip")
        checked += 1
    assert checked > 0


def test_all_large_moe_params_have_sharding_rules():
    _assert_large_leaves_sharded(CONFIGS["gpt2-moe-8e"])


def test_zero1_shards_all_large_optimizer_moments():
    """ZeRO-1's reason to exist: every ≥1M-element Adam moment must
    shard across data ranks (reference: FairScale OSS shards optimizer
    state, ray_ddp_sharded.py)."""
    import optax

    from ray_lightning_tpu.parallel.strategy import Zero1Strategy

    params = _abstract_params(CONFIGS["gpt2-small"])
    opt_state = jax.eval_shape(optax.adamw(1e-3).init, params)
    strategy = Zero1Strategy()
    mesh = strategy.build_mesh()
    checked = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(opt_state):
        if getattr(leaf, "ndim", 0) == 0 \
                or math.prod(leaf.shape) < 10**6:
            continue
        path_str = _path_str(path)
        spec = strategy.opt_spec(mesh, path_str, leaf)
        assert any(e is not None for e in spec), (
            f"opt leaf {path_str} {leaf.shape} not sharded")
        checked += 1
    assert checked > 0
