"""Core trainer tests: single-process, in-process SPMD over 8 virtual CPU
devices (the 'no plugin' path, plus strategy coverage)."""

import os

import jax
import numpy as np
import pytest

from ray_lightning_tpu import (
    EarlyStopping,
    ModelCheckpoint,
)
from ray_lightning_tpu.models import BoringModel, LightningMNISTClassifier

from tests.utils import get_trainer, load_test, predict_test, train_test


def test_devices_virtual():
    assert jax.device_count() == 8


def test_fit_boring(tmp_path, seed):
    trainer = get_trainer(str(tmp_path))
    train_test(trainer, BoringModel())


def test_metrics_logged(tmp_path, seed):
    trainer = get_trainer(str(tmp_path))
    trainer.fit(BoringModel())
    assert "loss" in trainer.callback_metrics
    assert "val_loss" in trainer.callback_metrics
    assert np.isfinite(trainer.callback_metrics["loss"])


def test_loss_decreases(tmp_path, seed):
    trainer = get_trainer(str(tmp_path), max_epochs=3,
                          limit_train_batches=16)
    module = BoringModel(lr=0.05)
    trainer.fit(module)
    # after 3 epochs driving outputs to zero, loss must shrink well
    assert trainer.callback_metrics["loss"] < 1.0


def test_validate_and_test_stages(tmp_path, seed):
    trainer = get_trainer(str(tmp_path))
    module = BoringModel()
    trainer.fit(module)
    val = trainer.validate(module)
    assert "val_loss" in val[0]
    out = trainer.test(module)
    assert "test_loss" in out[0]


def test_predict_returns_outputs(tmp_path, seed):
    trainer = get_trainer(str(tmp_path))
    module = BoringModel()
    trainer.fit(module)
    outputs = trainer.predict(module)
    assert len(outputs) > 0
    assert np.concatenate([np.asarray(o) for o in outputs]).shape[1] == 2


def test_mnist_learns(tmp_path, seed):
    trainer = get_trainer(str(tmp_path), max_epochs=3,
                          limit_train_batches=16, limit_val_batches=4)
    predict_test(trainer, LightningMNISTClassifier())


def test_checkpoint_saved_and_loads(tmp_path, seed):
    trainer = get_trainer(str(tmp_path))
    load_test(trainer, BoringModel())


def test_resume_from_checkpoint(tmp_path, seed):
    trainer = get_trainer(str(tmp_path), max_epochs=1)
    module = BoringModel()
    trainer.fit(module)
    ckpt = trainer.checkpoint_callback.best_model_path
    trainer2 = get_trainer(str(tmp_path), max_epochs=2)
    module2 = BoringModel()
    trainer2.fit(module2, ckpt_path=ckpt)
    assert trainer2.current_epoch >= 1
    assert trainer2.global_step > trainer.global_step


def test_early_stopping(tmp_path, seed):
    """EarlyStopping halts before max_epochs (test_ddp.py:287-306 shape)."""
    es = EarlyStopping(monitor="val_loss", patience=1, mode="min",
                       min_delta=100.0)  # impossible improvement bar
    trainer = get_trainer(str(tmp_path), max_epochs=20, callbacks=[es])
    trainer.fit(BoringModel())
    assert trainer.current_epoch < 20


def test_model_checkpoint_monitor_best(tmp_path, seed):
    mc = ModelCheckpoint(monitor="val_loss", mode="min", save_top_k=1,
                         dirpath=str(tmp_path / "ckpts"))
    trainer = get_trainer(str(tmp_path), max_epochs=3, callbacks=[mc],
                          checkpoint=False)
    trainer.callbacks.append(mc) if mc not in trainer.callbacks else None
    trainer.fit(BoringModel(lr=0.05))
    assert mc.best_model_path
    assert os.path.exists(mc.best_model_path)
    assert mc.best_model_score is not None


def test_max_steps(tmp_path, seed):
    trainer = get_trainer(str(tmp_path), max_epochs=10, max_steps=5)
    trainer.fit(BoringModel())
    assert trainer.global_step == 5


def test_steps_per_execution_matches_per_step(tmp_path, seed):
    """k steps folded into one compiled scan must train identically to k
    sequential dispatches: same final weights, same step count (the
    learning-curve guarantee for VERDICT item 3)."""
    from ray_lightning_tpu.parallel.gather import fetch_tree

    def run(k):
        trainer = get_trainer(str(tmp_path), max_epochs=1,
                              limit_train_batches=16,
                              steps_per_execution=k)
        module = BoringModel(batch_size=8, lr=0.05, dataset_length=128)
        trainer.fit(module)
        return trainer, fetch_tree(trainer.state.params)

    t1, p1 = run(1)
    t4, p4 = run(4)
    assert t1.global_step == t4.global_step == 16
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p4)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    # epoch-mean metrics survive the mixed scalar/[k] accumulator
    assert np.isfinite(t4.callback_metrics["loss"])


def test_cache_train_dataset_matches_streamed(tmp_path, seed):
    """Device-resident dataset + on-device index gather must train
    identically to streamed batches for epoch 0 (same order)."""
    from ray_lightning_tpu.parallel.gather import fetch_tree

    def run(**kw):
        trainer = get_trainer(str(tmp_path), max_epochs=1,
                              limit_train_batches=16, **kw)
        module = BoringModel(batch_size=8, lr=0.05, dataset_length=128)
        trainer.fit(module)
        return trainer, fetch_tree(trainer.state.params)

    t_stream, p_stream = run()
    t_cached, p_cached = run(steps_per_execution=4,
                             cache_train_dataset=True)
    assert t_stream.global_step == t_cached.global_step == 16
    for a, b in zip(jax.tree_util.tree_leaves(p_stream),
                    jax.tree_util.tree_leaves(p_cached)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_cache_train_dataset_multi_epoch_learns(tmp_path, seed):
    """Across epochs the cached path reshuffles batch order and keeps
    training (loss shrinks); step accounting stays exact."""
    trainer = get_trainer(str(tmp_path), max_epochs=3,
                          limit_train_batches=16,
                          steps_per_execution=4, cache_train_dataset=True)
    module = BoringModel(batch_size=8, lr=0.05, dataset_length=128)
    trainer.fit(module)
    assert trainer.global_step == 48
    assert trainer.callback_metrics["loss"] < 1.0


def test_cache_train_dataset_respects_max_steps(tmp_path, seed):
    trainer = get_trainer(str(tmp_path), max_epochs=10, max_steps=6,
                          steps_per_execution=4, cache_train_dataset=True)
    trainer.fit(BoringModel(batch_size=8, dataset_length=128))
    assert trainer.global_step == 6


def test_chunked_limit_counts_loader_positions(tmp_path, seed):
    """limit_train_batches counts loader positions in BOTH dispatch
    paths: with a short (skipped) final batch in the stream, k=1 and
    k=4 must run the same step count (review regression guard)."""
    # 68 rows / batch 8 -> 8 full batches + one short batch of 4 that
    # _batch_ok skips on the 8-shard mesh
    def run(k):
        trainer = get_trainer(str(tmp_path), max_epochs=1,
                              limit_train_batches=9, checkpoint=False,
                              steps_per_execution=k)
        trainer.fit(BoringModel(batch_size=8, dataset_length=68))
        return trainer.global_step

    assert run(1) == run(4) == 8


def test_steps_per_execution_respects_max_steps(tmp_path, seed):
    """A chunk never overshoots max_steps: 6 = one 4-chunk + 2 single
    tail steps, no recompile for the ragged tail."""
    trainer = get_trainer(str(tmp_path), max_epochs=10, max_steps=6,
                          steps_per_execution=4)
    trainer.fit(BoringModel(batch_size=8))
    assert trainer.global_step == 6


def test_steps_per_execution_val_interval_boundary(tmp_path, seed):
    """Chunks clamp to val_check_interval so mid-epoch validation still
    happens on schedule."""
    evals = []

    class CountVal(EarlyStopping):
        def __init__(self):
            super().__init__(monitor="val_loss", patience=10**6)

        def on_validation_end(self, trainer, module):
            evals.append(trainer.global_step)
            super().on_validation_end(trainer, module)

    trainer = get_trainer(str(tmp_path), max_epochs=1,
                          limit_train_batches=12, val_check_interval=3,
                          steps_per_execution=8,
                          callbacks=[CountVal()])
    trainer.fit(BoringModel(batch_size=8, dataset_length=128))
    assert evals[:4] == [3, 6, 9, 12]


def test_gradient_accumulation(tmp_path, seed):
    trainer = get_trainer(str(tmp_path), accumulate_grad_batches=2)
    module = BoringModel(batch_size=4)
    trainer.fit(module)
    assert "loss" in trainer.callback_metrics


def test_gradient_clipping(tmp_path, seed):
    trainer = get_trainer(str(tmp_path), gradient_clip_val=0.1)
    trainer.fit(BoringModel())
    assert np.isfinite(trainer.callback_metrics["loss"])


@pytest.mark.parametrize("strategy", ["ddp", "zero1", "fsdp"])
def test_strategies_train(tmp_path, seed, strategy):
    """Every sharding strategy trains the same model to a moving-weights
    state on the 8-device mesh."""
    trainer = get_trainer(str(tmp_path), strategy=strategy)
    train_test(trainer, BoringModel(batch_size=8))


def test_zero1_opt_state_is_sharded(tmp_path, seed):
    trainer = get_trainer(str(tmp_path), strategy="zero1", max_epochs=1,
                          limit_train_batches=2)
    module = BoringModel(batch_size=8, dataset_length=64)
    trainer.fit(module)
    # Adam-free SGD has no per-param opt state; use the kernel of a model
    # with adam instead: check sharding on the mnist classifier.
    trainer2 = get_trainer(str(tmp_path), strategy="zero1", max_epochs=1,
                           limit_train_batches=2)
    m2 = LightningMNISTClassifier(config={"batch_size": 32})
    trainer2.fit(m2)
    shardings = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda x: x.sharding,
                               trainer2.state.opt_state))
    assert any(
        any(ax is not None for ax in s.spec) for s in shardings
        if hasattr(s, "spec")), "no opt-state leaf is sharded under zero1"


def test_strategy_results_match_ddp_vs_zero1(tmp_path, seed):
    """ZeRO-1 must be numerically equivalent to DDP (same seed/data)."""
    from tests.conftest import assert_tree_allclose
    results = {}
    for name in ("ddp", "zero1"):
        trainer = get_trainer(str(tmp_path) + name, strategy=name,
                              max_epochs=1, limit_train_batches=4,
                              checkpoint=False, seed=123)
        module = LightningMNISTClassifier(config={"batch_size": 32})
        trainer.fit(module)
        results[name] = module._trained_variables["params"]
    assert_tree_allclose(results["ddp"], results["zero1"],
                         rtol=2e-4, atol=1e-5)


def test_fit_then_refit_reuses_weights(tmp_path, seed):
    module = BoringModel(lr=0.05)
    t1 = get_trainer(str(tmp_path), max_epochs=1)
    t1.fit(module)
    w1 = module._trained_variables["params"]
    t2 = get_trainer(str(tmp_path), max_epochs=1, checkpoint=False)
    t2.fit(module)
    w2 = module._trained_variables["params"]
    deltas = [np.linalg.norm(np.asarray(a) - np.asarray(b))
              for a, b in zip(jax.tree_util.tree_leaves(w1),
                              jax.tree_util.tree_leaves(w2))]
    assert sum(deltas) > 0  # continued training moved weights further


# -- the uses_rng contract (VERDICT r3 weak #4) ------------------------------


def test_uses_rng_false_make_rng_raises(tmp_path, seed):
    """A False-declaring module that calls ctx.make_rng must fail at
    trace time with the documented error (core/module.py uses_rng),
    not silently train with a missing key."""

    class _Cheater(BoringModel):
        uses_rng = False

        def training_step(self, ctx, batch):
            ctx.make_rng()   # contract violation
            return super().training_step(ctx, batch)

    trainer = get_trainer(str(tmp_path))
    with pytest.raises(RuntimeError, match="No PRNG key"):
        trainer.fit(_Cheater())


def test_uses_rng_trajectory_equality(tmp_path, seed):
    """For a module that never consumes randomness, uses_rng=True vs
    False must produce the IDENTICAL loss trajectory — the flag only
    drops PRNG bookkeeping, never math."""

    class _SameButTrue(BoringModel):
        uses_rng = True

    losses = {}
    for cls in (BoringModel, _SameButTrue):
        trainer = get_trainer(str(tmp_path / cls.__name__), max_epochs=2,
                              limit_train_batches=8)
        mod = cls(lr=0.05)
        traj = []
        from ray_lightning_tpu.core.callbacks import Callback

        class _Tracker(Callback):
            def on_train_batch_end(self, trainer, module, outputs, batch,
                                   idx):
                traj.append(float(np.asarray(outputs["loss"]).ravel()[-1]))

        trainer.callbacks.append(_Tracker())
        trainer.fit(mod)
        losses[cls.uses_rng] = traj
    assert losses[True], "no losses recorded"
    np.testing.assert_allclose(losses[True], losses[False], rtol=0,
                               atol=0, err_msg="uses_rng flag changed math")


def test_uses_rng_false_with_grad_accumulation(tmp_path, seed):
    """accumulate_grad_batches>1 with step_rng=None (uses_rng=False)
    must run the micro-batch fold without touching the absent key
    (core/steps.py rng_i=None branch) and match the unaccumulated run
    to fp tolerance on a linear model.

    The accumulated step splits each LOADER batch into k microbatches,
    averages grads in fp32 and applies ONE optimizer step
    (core/steps.py build_train_step) — a pure memory knob, so the twin
    is the SAME run at accumulate=1: per-step losses and final weights
    must agree to fp tolerance, which fails if the rng_i=None fold
    breaks math, not only if it crashes (VERDICT r4 weak #3)."""
    from ray_lightning_tpu.core.callbacks import Callback

    def run(subdir, accumulate):
        traj = []

        class _Tracker(Callback):
            def on_train_batch_end(self, trainer, module, outputs, batch,
                                   idx):
                traj.append(float(np.asarray(outputs["loss"]).ravel()[-1]))

        t = get_trainer(str(tmp_path / subdir), max_epochs=1,
                        limit_train_batches=4,
                        accumulate_grad_batches=accumulate)
        m = BoringModel(lr=0.05)
        assert not m.uses_rng
        t.callbacks.append(_Tracker())
        t.fit(m)
        return t, traj

    t1, acc_traj = run("acc", 2)
    assert t1.global_step == 4
    assert np.isfinite(t1.callback_metrics["loss"])
    assert len(acc_traj) == 4 and np.all(np.isfinite(acc_traj))

    t0, plain_traj = run("plain", 1)
    np.testing.assert_allclose(acc_traj, plain_traj, rtol=1e-5, atol=1e-6,
                               err_msg="accumulated fold changed math")
    for a, b in zip(jax.tree_util.tree_leaves(t1.state.params),
                    jax.tree_util.tree_leaves(t0.state.params)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-5, atol=1e-6)


# -- conditional state donation (round 5) -----------------------------------


def test_donation_is_perf_only(tmp_path, seed, monkeypatch):
    """RLT_DONATE=0 vs 1 must produce IDENTICAL training runs — donation
    is buffer aliasing, never math (the round-5 heuristic skips it on
    small states for the measured ~3% device win; this is the guard
    that the knob can never change results)."""
    from ray_lightning_tpu.core.callbacks import Callback

    def run(flag):
        monkeypatch.setenv("RLT_DONATE", flag)
        traj = []

        class Track(Callback):
            def on_train_batch_end(self, trainer, module, outputs, batch,
                                   idx):
                traj.append(float(np.asarray(outputs["loss"]).ravel()[-1]))

        t = get_trainer(str(tmp_path / f"d{flag}"), max_epochs=1,
                        limit_train_batches=6, limit_val_batches=0,
                        checkpoint=False, callbacks=[Track()])
        t.fit(BoringModel(lr=0.05))
        return traj

    np.testing.assert_allclose(run("1"), run("0"), rtol=0, atol=0,
                               err_msg="donation changed training math")


def test_should_donate_heuristic(tmp_path, seed, monkeypatch):
    """Auto mode donates when the device budget is unknown (virtual CPU
    meshes — keeps every memory-fit audit valid); RLT_DONATE forces
    either way; a typo'd value warns and falls through to auto; an
    unbounded dataset cache forces donation even under a known budget
    (the cache shares the HBM the skip would spend)."""
    t = get_trainer(str(tmp_path), checkpoint=False)
    t.fit(BoringModel())          # builds _mesh/_abstract_state
    abstract = t._abstract_state
    sh = t._state_shardings
    monkeypatch.delenv("RLT_DONATE", raising=False)
    assert t._should_donate(abstract, sh)       # CPU: budget unknown
    monkeypatch.setenv("RLT_DONATE", "0")
    assert not t._should_donate(abstract, sh)
    monkeypatch.setenv("RLT_DONATE", "1")
    assert t._should_donate(abstract, sh)
    monkeypatch.setenv("RLT_DONATE", "yes")
    with pytest.warns(UserWarning, match="RLT_DONATE"):
        assert t._should_donate(abstract, sh)   # auto on CPU: donate
    # known budget + small state -> skip; unbounded cache -> donate
    monkeypatch.delenv("RLT_DONATE", raising=False)
    monkeypatch.setattr(type(t), "_device_memory_budget",
                        lambda self: 16 << 30)
    assert not t._should_donate(abstract, sh)   # tiny state, no cache
    t.cache_train_dataset = True
    t._cache_bytes_hint = None
    assert t._should_donate(abstract, sh)       # cache size unknown
    t._cache_bytes_hint = 16 << 30
    assert t._should_donate(abstract, sh)       # cache exhausts the budget
    t._cache_bytes_hint = 1 << 20
    assert not t._should_donate(abstract, sh)   # small cache: still skip


def test_donation_decision_table(seed, monkeypatch):
    """Pin the per-config auto-donation decisions (VERDICT top_next):
    the memory-fit audits (tests/test_memory_fit.py) compile their
    programs with ``donate_argnums=0`` EXPLICITLY, so what the heuristic
    actually picks per config is otherwise invisible — this table makes
    a change on either side (heuristic constants, sharding math, config
    sizes) fail loudly instead of silently diverging from the audited
    budget story.  Notable pinned rows: 1.3B ZeRO-1 donates on v5e
    (16 GB) but SKIPS donation on v4 (32 GB, ~2.85 GB/device state at
    data=64) — the v4 fit therefore runs the UN-donated program, whose
    peak carries old+new state; the audits' budget math must keep
    covering that (the heuristic's 2.5x/0.3 cut guarantees >= 2x state
    headroom at the skip boundary by construction)."""
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.core.steps import build_init_fn
    from ray_lightning_tpu.models.gpt import GPTLightningModule
    from ray_lightning_tpu.parallel.strategy import resolve_strategy

    monkeypatch.delenv("RLT_DONATE", raising=False)
    GB = 1 << 30

    def abstract_and_shardings(module, strategy):
        strat = resolve_strategy(strategy)
        module.setup_model()
        tx = module.configure_optimizers()
        mesh = strat.build_mesh(batch_hint=8)
        batch = jax.tree_util.tree_map(
            np.asarray, next(iter(module.train_dataloader())))
        abstract = jax.eval_shape(build_init_fn(module, tx),
                                  jax.random.PRNGKey(0), batch)
        return strat, abstract, strat.state_shardings(mesh, abstract)

    def decide(module, strategy, budget):
        _, abstract, sh = abstract_and_shardings(module, strategy)
        t = Trainer(enable_checkpointing=False, logger=False)
        t._device_memory_budget = lambda: budget
        return t._should_donate(abstract, sh), abstract

    # the measured small-state win region on v5e: donation skipped
    got, _ = decide(BoringModel(batch_size=16), "ddp", 16 * GB)
    assert got is False
    got, _ = decide(GPTLightningModule("gpt2-small", dataset_size=8,
                                       batch_size=8), "ddp", 16 * GB)
    assert got is False
    # 1.3B zero1 on v5e-8: state/device too large, donation required
    got, abstract_1p3b = decide(
        GPTLightningModule("gpt2-1p3b", dataset_size=8, batch_size=8),
        "zero1", 16 * GB)
    assert got is True
    # unknown budget (virtual CPU, profiler-less tunnels): donate
    t = Trainer(enable_checkpointing=False, logger=False)
    t._device_memory_budget = lambda: None
    _, abstract, sh = abstract_and_shardings(
        BoringModel(batch_size=16), "ddp")
    assert t._should_donate(abstract, sh) is True

    # v4-128 (data=64, 32 GB/chip): the same 1.3B zero1 state shards to
    # ~2.85 GB/device and the heuristic SKIPS donation — the pinned
    # divergence row (the fit audits compile donated regardless)
    from tests.test_memory_fit import _state_bytes_at_dp
    per_dev = _state_bytes_at_dp(resolve_strategy("zero1"),
                                 abstract_1p3b, 64)
    assert 2.5 * GB < per_dev < 3.2 * GB, per_dev / GB
    assert Trainer._donation_cutoff(per_dev, 32 * GB) is False
    assert Trainer._donation_cutoff(per_dev, 16 * GB) is True
