"""Shared behavioral assertions (reference: tests/utils.py:151-210).

- ``get_trainer``: trainer factory with CI-sized limits (utils.py:151-171)
- ``train_test``: weights actually changed after remote training and
  round-tripped to the driver (utils.py:174-183)
- ``load_test``: the best checkpoint file loads (utils.py:186-191)
- ``predict_test``: trained classifier beats chance — end-to-end learning
  signal (utils.py:194-210)
"""

from __future__ import annotations

import os

import jax
import numpy as np

from ray_lightning_tpu import RayXlaPlugin, Trainer


def cpu_plugin(num_workers=2, **kw):
    """Distributed plugin over CPU subprocess workers — the test-time
    stand-in for TPU hosts (as gloo stood in for NCCL in the reference,
    ray_ddp.py:149-151)."""
    return RayXlaPlugin(num_workers=num_workers, platform="cpu", **kw)


def get_trainer(root_dir, plugins=None, max_epochs: int = 1,
                limit_train_batches: int = 10, limit_val_batches: int = 2,
                callbacks=None, checkpoint: bool = True, strategy=None,
                **kwargs):
    return Trainer(
        default_root_dir=root_dir,
        callbacks=callbacks,
        plugins=plugins,
        strategy=strategy,
        max_epochs=max_epochs,
        limit_train_batches=limit_train_batches,
        limit_val_batches=limit_val_batches,
        enable_checkpointing=checkpoint,
        num_sanity_val_steps=0,
        log_every_n_steps=1,
        **kwargs,
    )


def _flat_norm_delta(before, after) -> float:
    total = 0.0
    for a, b in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(after)):
        total += float(np.linalg.norm(np.asarray(a) - np.asarray(b)))
    return total


def initial_params(module):
    """Initialize a copy of the module's params on the driver for
    before/after comparison."""
    import jax.numpy as jnp
    module.setup_model()
    batch = next(iter(module.train_dataloader()))
    x = batch[0] if isinstance(batch, (tuple, list)) else batch
    variables = module.model.init(jax.random.PRNGKey(0), jnp.asarray(x))
    return jax.device_get(variables["params"])


def train_test(trainer, module):
    """Train and assert the driver-visible weights moved
    (utils.py:174-183 analog)."""
    before = initial_params(module)
    trainer.fit(module)
    after = module._trained_variables["params"]
    assert _flat_norm_delta(before, after) > 0.1


def load_test(trainer, module):
    """Best checkpoint exists and loads (utils.py:186-191 analog)."""
    trainer.fit(module)
    ckpt_path = trainer.checkpoint_callback.best_model_path
    assert ckpt_path and os.path.exists(ckpt_path), ckpt_path
    ckpt = Trainer.load_checkpoint_dict(ckpt_path)
    assert "state" in ckpt and "params" in ckpt["state"]


def predict_test(trainer, module, datamodule=None):
    """Fit then predict; accuracy must beat chance
    (utils.py:194-210 analog)."""
    trainer.fit(module, datamodule)
    outputs = trainer.predict(module, datamodule)
    preds = np.concatenate([np.asarray(o) for o in outputs])
    loader = (datamodule.predict_dataloader() if datamodule is not None
              else module.predict_dataloader())
    labels = []
    for batch in loader:
        labels.append(np.asarray(batch[1]))
    labels = np.concatenate(labels)[:len(preds)]
    acc = float((preds == labels).mean())
    assert acc >= 0.5, f"accuracy {acc} below 0.5"
