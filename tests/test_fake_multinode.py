"""Fake multi-node topology on one machine: actors claim distinct node
IPs via RLT_NODE_IP_OVERRIDE and the real RPC path feeds the plugin's
rank-assignment — the single-box analog of the reference's two-raylet
cluster fixture (ray.cluster_utils.Cluster, test_ddp.py:52-60) and its
fake-IP rank tests (test_ddp.py:78-112)."""

from ray_lightning_tpu.cluster.executor import RLTExecutor
from ray_lightning_tpu.cluster.local import LocalBackend
from ray_lightning_tpu.plugins.xla import RayXlaPlugin
from ray_lightning_tpu.util import process_results


def test_fake_two_node_topology_end_to_end():
    backend = LocalBackend()
    try:
        # 4 workers: ranks 0,2 on "node 1"; ranks 1,3 on "node 2"
        actors = [
            backend.create_actor(
                RLTExecutor,
                env={"RLT_NODE_IP_OVERRIDE": "1" if i % 2 == 0 else "2"},
                name=f"fake-node-{i}")
            for i in range(4)
        ]
        info = process_results(
            [a.call("get_node_and_device_info") for a in actors], backend)
        assert [d["ip"] for d in info] == ["1", "2", "1", "2"]

        ranks = RayXlaPlugin._assign_local_ranks(info)
        assert ranks[0] == (0, 0)
        assert ranks[2] == (0, 1)
        assert ranks[1] == (1, 0)
        assert ranks[3] == (1, 1)

        # the coordinator-address plumbing also sees the faked IP
        ip = actors[0].call("get_node_ip").result(timeout=60)
        assert ip == "1"
        for a in actors:
            a.kill()
    finally:
        backend.shutdown()
