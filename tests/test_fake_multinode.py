"""Fake multi-node topology on one machine: actors claim distinct node
IPs via RLT_NODE_IP_OVERRIDE and the real RPC path feeds the plugin's
rank-assignment — the single-box analog of the reference's two-raylet
cluster fixture (ray.cluster_utils.Cluster, test_ddp.py:52-60) and its
fake-IP rank tests (test_ddp.py:78-112).  Also the TPU chip-partition
env plumbing (_share_cuda_visible_devices analog, ray_ddp.py:221-265)."""

import pytest

from ray_lightning_tpu.cluster.executor import RLTExecutor
from ray_lightning_tpu.cluster.local import LocalBackend
from ray_lightning_tpu.plugins.xla import RayXlaPlugin
from ray_lightning_tpu.util import process_results
from ray_lightning_tpu.utils.tpu_topology import partition_env, process_bounds


def test_fake_two_node_topology_end_to_end():
    backend = LocalBackend()
    try:
        # 4 workers: ranks 0,2 on "node 1"; ranks 1,3 on "node 2"
        actors = [
            backend.create_actor(
                RLTExecutor,
                env={"RLT_NODE_IP_OVERRIDE": "1" if i % 2 == 0 else "2"},
                name=f"fake-node-{i}")
            for i in range(4)
        ]
        info = process_results(
            [a.call("get_node_and_device_info") for a in actors], backend)
        assert [d["ip"] for d in info] == ["1", "2", "1", "2"]

        ranks = RayXlaPlugin._assign_local_ranks(info)
        assert ranks[0] == (0, 0)
        assert ranks[2] == (0, 1)
        assert ranks[1] == (1, 0)
        assert ranks[3] == (1, 1)

        # the coordinator-address plumbing also sees the faked IP
        ip = actors[0].call("get_node_ip").result(timeout=60)
        assert ip == "1"
        for a in actors:
            a.kill()
    finally:
        backend.shutdown()


def test_process_bounds_tilings():
    """Every supported (chips/worker, workers/host) split maps to the
    topology slabs libtpu expects."""
    assert process_bounds(1, 4) == ("1,1,1", "2,2,1")   # v4-8 → 4 procs
    assert process_bounds(2, 2) == ("1,2,1", "2,1,1")   # v4-8 → 2 procs
    assert process_bounds(1, 2) == ("1,1,1", "1,2,1")   # chip pair
    assert process_bounds(2, 4) == ("1,2,1", "2,2,1")   # 8-chip host
    assert process_bounds(4, 2) == ("2,2,1", "1,2,1")   # 8-chip host


def test_impossible_splits_error():
    with pytest.raises(ValueError, match="cannot split"):
        process_bounds(3, 2)       # 3 chips is not a rectangular slab
    with pytest.raises(ValueError, match="cannot split"):
        process_bounds(4, 4)       # 16 chips is not one host


def test_partition_env_contents():
    env = partition_env(2, 1, "10.0.0.5", [4001, 4002])
    assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,2,1"
    assert env["TPU_PROCESS_BOUNDS"] == "2,1,1"
    assert env["TPU_VISIBLE_CHIPS"] == "2,3"
    assert env["TPU_VISIBLE_DEVICES"] == "2,3"
    assert env["TPU_PROCESS_ADDRESSES"] == "10.0.0.5:4001,10.0.0.5:4002"
    assert env["TPU_PROCESS_PORT"] == "4002"
    assert env["CLOUD_TPU_TASK_ID"] == "1"


def test_colocated_tpu_workers_get_disjoint_chip_env():
    """Two fake hosts x two TPU workers each: every co-located worker
    must receive its own chip slice, the pair's shared rendezvous
    addresses, and its local task id — asserted from INSIDE the worker
    process after the plugin's env fan-out (VERDICT missing #4)."""
    def read_tpu_env():  # nested so cloudpickle ships it by value
        import os as _os
        return {k: v for k, v in _os.environ.items()
                if k.startswith("TPU_") or k == "CLOUD_TPU_TASK_ID"}

    backend = LocalBackend()
    try:
        actors = [
            backend.create_actor(
                RLTExecutor,
                env={"RLT_NODE_IP_OVERRIDE": "1" if i % 2 == 0 else "2"},
                name=f"tpu-split-{i}")
            for i in range(4)
        ]
        info = process_results(
            [a.call("get_node_and_device_info") for a in actors], backend)
        plugin = RayXlaPlugin(num_workers=4, use_tpu=True,
                              devices_per_worker=2)
        plugin._workers = actors
        ranks = plugin._assign_local_ranks(info)
        envs = plugin._tpu_partition_envs(info, ranks, backend)
        assert set(envs) == {0, 1, 2, 3}  # every worker shares a host

        process_results(
            [a.call("set_env_vars", envs[i]) for i, a in enumerate(actors)],
            backend)
        seen = process_results(
            [a.call("execute", read_tpu_env) for a in actors], backend)

        for node_ip, members in (("1", [0, 2]), ("2", [1, 3])):
            chip_sets = []
            addrs = set()
            for i in members:
                env = seen[i]
                assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,2,1"
                assert env["TPU_PROCESS_BOUNDS"] == "2,1,1"
                assert env["TPU_PROCESS_ADDRESSES"].startswith(
                    f"{node_ip}:")
                chip_sets.append(set(env["TPU_VISIBLE_CHIPS"].split(",")))
                addrs.add(env["TPU_PROCESS_ADDRESSES"])
                assert env["CLOUD_TPU_TASK_ID"] == str(ranks[i][1])
            # disjoint chips covering the host; one shared rendezvous
            assert chip_sets[0].isdisjoint(chip_sets[1])
            assert chip_sets[0] | chip_sets[1] == {"0", "1", "2", "3"}
            assert len(addrs) == 1

        for a in actors:
            a.kill()
    finally:
        backend.shutdown()


def test_sole_host_owner_needs_no_scoping():
    """A worker alone on its node owns the whole host: no TPU_* env."""
    info = [{"ip": "1"}, {"ip": "2"}]
    plugin = RayXlaPlugin(num_workers=2, use_tpu=True,
                          devices_per_worker=4)
    ranks = plugin._assign_local_ranks(info)
    assert plugin._tpu_partition_envs(info, ranks, backend=None) == {}
