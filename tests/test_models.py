"""Model-family coverage for the BASELINE workloads beyond GPT:
ResNet (config #2 — BatchNorm state through the compiled step) and BERT
fine-tuning (config #4 — ZeRO-1 sharding)."""

import numpy as np

from ray_lightning_tpu import Trainer
from ray_lightning_tpu.models.bert import (
    CONFIGS as BERT_CONFIGS,
    BertLightningModule,
)
from ray_lightning_tpu.models.resnet import (
    ResNetConfig,
    ResNetLightningModule,
    synthetic_cifar10,
)


def tiny_resnet(**kw):
    cfg = ResNetConfig(stage_sizes=(1, 1), width=8, bottleneck=False)
    kw.setdefault("lr", 0.05)
    return ResNetLightningModule(cfg, batch_size=8, train_size=64,
                                 val_size=32, **kw)


def small_trainer(tmp_path, max_epochs=1, **kw):
    kw.setdefault("limit_train_batches", 6)
    kw.setdefault("limit_val_batches", 2)
    return Trainer(max_epochs=max_epochs, num_sanity_val_steps=0,
                   enable_checkpointing=False, seed=0,
                   default_root_dir=str(tmp_path), **kw)


# -- ResNet ---------------------------------------------------------------

def test_resnet_forward_shapes(seed):
    import jax
    from ray_lightning_tpu.models.resnet import ResNet
    cfg = ResNetConfig(stage_sizes=(1, 1), width=8, bottleneck=True)
    model = ResNet(cfg)
    x = np.zeros((2, 32, 32, 3), np.float32)
    variables = model.init(jax.random.PRNGKey(0), x, False)
    assert "batch_stats" in variables  # BN statistics collection exists
    logits = model.apply(variables, x, False)
    assert logits.shape == (2, 10)
    assert logits.dtype == np.float32


def test_resnet_trains_and_bn_state_updates(tmp_path, seed):
    module = tiny_resnet()
    trainer = small_trainer(tmp_path, max_epochs=2)
    trainer.fit(module)
    assert np.isfinite(trainer.callback_metrics["loss"])
    # BatchNorm running MEANS must have moved off their zero init — the
    # guard that mutable batch_stats actually thread through the
    # compiled step (vars init to 1, so only means discriminate)
    import jax
    bs = module._trained_variables["model_state"]["batch_stats"]
    flat = jax.tree_util.tree_flatten_with_path(bs)[0]
    means = [np.asarray(leaf) for path, leaf in flat
             if "mean" in "/".join(getattr(p, "key", str(p))
                                   for p in path)]
    assert means, "no BatchNorm mean leaves found"
    assert sum(float(np.abs(m).sum()) for m in means) > 0


def test_resnet_learns(tmp_path, seed):
    module = tiny_resnet(lr=0.2)
    trainer = small_trainer(tmp_path, max_epochs=10,
                            limit_train_batches=None)
    trainer.fit(module)
    assert trainer.callback_metrics["train_accuracy"] > 0.5


def test_resnet_eval_uses_running_stats(tmp_path, seed):
    """predict/test must run BN in inference mode (running averages) —
    the same input yields the same logits regardless of batch mix."""
    module = tiny_resnet()
    trainer = small_trainer(tmp_path)
    trainer.fit(module)
    model = module.model
    variables = {"params": module._trained_variables["params"],
                 **module._trained_variables["model_state"]}
    x = np.asarray(synthetic_cifar10(8, seed=3).take(np.arange(8))[0])
    solo = model.apply(variables, x[:1], False)
    mixed = model.apply(variables, x, False)[:1]
    np.testing.assert_allclose(np.asarray(solo), np.asarray(mixed),
                               rtol=2e-2, atol=2e-2)


def test_resnet_ddp_across_actors(tmp_path, seed):
    """BASELINE config #2 shape: ResNet via RayXlaPlugin DDP — BatchNorm
    statistics and weights round-trip from the actors to the driver."""
    from tests.utils import cpu_plugin
    module = tiny_resnet()
    trainer = small_trainer(tmp_path, plugins=[cpu_plugin(2)])
    trainer.fit(module)
    assert np.isfinite(trainer.callback_metrics["loss"])
    assert "batch_stats" in module._trained_variables["model_state"]


def test_synthetic_cifar_separable():
    """Nearest-class-mean on held-out draws must beat chance by a wide
    margin — the property test_resnet_learns depends on."""
    train = synthetic_cifar10(512, seed=0)
    test = synthetic_cifar10(128, seed=9)
    xtr, ytr = train.take(np.arange(512))
    xte, yte = test.take(np.arange(128))
    assert xtr.shape == (512, 32, 32, 3)
    means = np.stack([xtr[ytr == c].mean(axis=0) for c in range(10)])
    d = ((xte[:, None] - means[None]) ** 2).sum(axis=(2, 3, 4))
    acc = (d.argmin(axis=1) == yte).mean()
    assert acc > 0.8, f"synthetic cifar barely separable: acc={acc}"


def test_resnet50_config_is_default():
    m = ResNetLightningModule()
    assert m.config.stage_sizes == (3, 4, 6, 3) and m.config.bottleneck


# -- BERT -----------------------------------------------------------------

def test_bert_forward_shapes(seed):
    import jax
    from ray_lightning_tpu.models.bert import BertClassifier
    cfg = BERT_CONFIGS["tiny"]
    model = BertClassifier(cfg)
    tokens = np.zeros((2, cfg.max_len), np.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(variables, tokens)
    assert logits.shape == (2, cfg.num_classes)


def test_bert_finetune_learns(tmp_path, seed):
    module = BertLightningModule("tiny", lr=3e-4, batch_size=8,
                                 train_size=128, val_size=32)
    trainer = small_trainer(tmp_path, max_epochs=4,
                            limit_train_batches=None)
    trainer.fit(module)
    assert trainer.callback_metrics["train_accuracy"] > 0.7


def test_bert_zero1_matches_ddp_loss(tmp_path, seed):
    """The BASELINE #4 shape: BERT fine-tune under ZeRO-1 must produce
    the same loss trajectory as plain DDP (sharding is an optimization,
    not a semantics change)."""
    losses = {}
    for strategy in ("ddp", "zero1"):
        module = BertLightningModule("tiny", batch_size=8, train_size=64)
        trainer = small_trainer(tmp_path / strategy, strategy=strategy)
        trainer.fit(module)
        losses[strategy] = trainer.callback_metrics["loss"]
    np.testing.assert_allclose(losses["ddp"], losses["zero1"],
                               rtol=1e-4, atol=1e-5)


def test_bert_partition_rules_split_the_big_params(seed):
    """The Megatron split rules (not the catch-all) must claim every
    tensor-parallel-relevant param: qkv/proj/fc/out kernels and the
    embedding table each match a rule with a sharded PartitionSpec."""
    import jax
    import re
    from ray_lightning_tpu.models.bert import (
        BertClassifier, bert_partition_rules)
    cfg = BERT_CONFIGS["tiny"]
    model = BertClassifier(cfg)
    tokens = np.zeros((2, cfg.max_len), np.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    rules = bert_partition_rules()

    def rule_spec(name):
        # unmatched params are legitimate: SpmdStrategy falls back to
        # replicate-or-fsdp (no catch-all rule shadows the fallback)
        for pat, spec in rules:
            if re.search(pat, name):
                return spec
        return None

    flat = jax.tree_util.tree_flatten_with_path(variables["params"])[0]
    names = ["/".join(getattr(p, "key", str(p)) for p in path)
             for path, _leaf in flat]
    sharded = {n for n in names
               if (spec := rule_spec(n)) is not None
               and any(ax is not None for ax in spec)}
    # every encoder layer's matmuls are tensor-split
    for i in range(cfg.n_layer):
        for part in ("attn/qkv/kernel", "attn/proj/kernel", "fc/kernel",
                     "out/kernel"):
            assert any(f"h{i}/" in n and n.endswith(part)
                       for n in sharded), (i, part, sorted(sharded))
    assert any(n.endswith("wte/embedding") for n in sharded)


def test_bert_mlm_forward_and_learns(tmp_path, seed):
    """MLM pretraining: logits over the vocab; loss decreases on
    structured token data within a short run."""
    from ray_lightning_tpu.models.bert import BertMLMModule
    module = BertMLMModule("tiny", lr=3e-3, batch_size=8, train_size=64,
                           val_size=16)
    losses = []

    from ray_lightning_tpu import Callback

    class Track(Callback):
        def on_train_epoch_end(self, trainer, m):
            losses.append(trainer.callback_metrics["loss"])

    trainer = small_trainer(tmp_path, max_epochs=6,
                            limit_train_batches=None,
                            callbacks=[Track()])
    trainer.fit(module)
    # structured data: MLM loss must fall clearly below its start
    assert losses[-1] < losses[0] - 0.3, losses


def test_bert_mlm_loss_counts_only_masked(seed):
    """With mask_prob→0 the (clamped) loss is 0 — unmasked positions
    contribute nothing."""
    import jax
    import numpy as np
    from ray_lightning_tpu.models.bert import (
        CONFIGS as BC, BertMLMModule)
    module = BertMLMModule("tiny", mask_prob=0.0)
    module.setup_model()
    tokens = np.zeros((2, BC["tiny"].max_len), np.int32)
    variables = module.model.init(jax.random.PRNGKey(0), tokens)

    class Ctx:
        training = True
        params = variables["params"]

        def apply(self, x, det):
            return module.model.apply(variables, x, det)

    loss = module._mlm_loss(Ctx(), tokens, jax.random.PRNGKey(1))
    assert float(loss) == 0.0
