"""Ring attention (sequence parallelism) tests on the 8-virtual-device
CPU mesh — the fake-multi-chip idiom (conftest.py), standing in for an
ICI ring exactly as the reference's gloo CI stands in for NCCL."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu.models.gpt import dot_product_attention
from ray_lightning_tpu.parallel.mesh import (
    build_device_mesh, set_current_mesh)
from ray_lightning_tpu.parallel.ring import (
    blockwise_attention, ring_attention)


@pytest.fixture(autouse=True)
def _clear_mesh():
    yield
    set_current_mesh(None)


def _rand_qkv(b=2, t=256, h=2, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, t, h, d), jnp.float32)
                 for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_blockwise_matches_naive(causal):
    q, k, v = _rand_qkv()
    out = blockwise_attention(q, k, v, causal=causal, dtype=jnp.float32,
                              block_size=64)
    ref = dot_product_attention(q, k, v, causal=causal, dtype=jnp.float32)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("ring", [2, 4, 8])
def test_ring_matches_naive(causal, ring):
    mesh = build_device_mesh(("data", "sequence"),
                             {"data": 1, "sequence": ring},
                             devices=jax.devices()[:ring])
    q, k, v = _rand_qkv()
    out = ring_attention(q, k, v, causal=causal, dtype=jnp.float32,
                         mesh=mesh)
    ref = dot_product_attention(q, k, v, causal=causal, dtype=jnp.float32)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_ring_with_data_and_tensor_axes():
    # mixed mesh: batch on data, heads on tensor, sequence ring of 2
    mesh = build_device_mesh(("data", "sequence", "tensor"),
                             {"data": 2, "sequence": 2, "tensor": 2})
    q, k, v = _rand_qkv(b=4, t=128, h=4, d=16)
    out = ring_attention(q, k, v, causal=True, dtype=jnp.float32, mesh=mesh)
    ref = dot_product_attention(q, k, v, causal=True, dtype=jnp.float32)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_ring_grads_match_naive():
    mesh = build_device_mesh(("data", "sequence"),
                             {"data": 1, "sequence": 4},
                             devices=jax.devices()[:4])
    q, k, v = _rand_qkv(t=128)

    def loss_ring(q, k, v):
        o = ring_attention(q, k, v, causal=True, dtype=jnp.float32,
                           mesh=mesh)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        o = dot_product_attention(q, k, v, causal=True, dtype=jnp.float32)
        return jnp.sum(jnp.sin(o))

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name} mismatch")


def test_ring_under_jit_with_sharded_inputs():
    mesh = build_device_mesh(("data", "sequence"),
                             {"data": 2, "sequence": 4})
    q, k, v = _rand_qkv(b=4, t=256)
    sh = jax.sharding.NamedSharding(mesh, P("data", "sequence"))
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))

    @jax.jit
    def f(q, k, v):
        return ring_attention(q, k, v, causal=True, dtype=jnp.float32,
                              mesh=mesh)

    out = f(q, k, v)
    ref = dot_product_attention(q, k, v, causal=True, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_gpt_ring_attention_end_to_end():
    """Full trainer path: SpmdStrategy with a sequence axis + GPT with
    attention_impl='ring' — the long-context configuration."""
    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.models.gpt import GPTConfig, GPTLightningModule
    from ray_lightning_tpu.parallel.strategy import SpmdStrategy

    cfg = GPTConfig(vocab_size=128, block_size=64, n_layer=1, n_head=2,
                    n_embd=32, remat=False, attention_impl="ring")
    module = GPTLightningModule(cfg, dataset_size=16, batch_size=8)
    strategy = SpmdStrategy(axis_names=("data", "sequence"),
                            axis_sizes={"sequence": 4},
                            shard_sequence_dim=True)
    trainer = Trainer(max_steps=2, max_epochs=1, strategy=strategy,
                      enable_checkpointing=False, num_sanity_val_steps=0,
                      limit_val_batches=0, log_every_n_steps=1)
    trainer.fit(module)
    assert trainer.global_step == 2
    assert np.isfinite(float(trainer.callback_metrics["loss"]))
