"""Sharded (orbax) checkpointing: per-shard async save, re-shard on
restore (utils/checkpoint.py, ShardedCheckpoint callback).

The reference's closest behavior is resume-with-fewer-workers
(test_ddp_sharded.py:119-138): optimizer state saved under one world
size must load under another.  Here that generalizes to restoring into
a DIFFERENT mesh without ever gathering the full state to one host.
"""

import numpy as np
import pytest

import jax

from ray_lightning_tpu import ShardedCheckpoint, Trainer
from ray_lightning_tpu.models.gpt import (GPTLightningModule,
                                          gpt_partition_rules)
from ray_lightning_tpu.parallel.strategy import SpmdStrategy
from ray_lightning_tpu.utils.checkpoint import (ShardedCheckpointer,
                                                abstract_like)
from ray_lightning_tpu.models import BoringModel
from tests.conftest import assert_tree_allclose


def _fit(tmp, strategy=None, max_steps=3, module=None, resume=None,
         callbacks=None):
    trainer = Trainer(max_epochs=10, max_steps=max_steps,
                      strategy=strategy, enable_checkpointing=False,
                      num_sanity_val_steps=0, limit_val_batches=0,
                      log_every_n_steps=1, callbacks=callbacks or [],
                      default_root_dir=tmp, seed=0,
                      resume_from_checkpoint=resume)
    trainer.fit(module or BoringModel())
    return trainer


def test_save_restore_roundtrip(tmp_path, seed):
    trainer = _fit(str(tmp_path))
    ckdir = str(tmp_path / "sharded")
    trainer.save_sharded_checkpoint(ckdir)
    trainer.wait_for_checkpoints()

    ck = ShardedCheckpointer(ckdir)
    assert ck.latest_step() == trainer.global_step
    state, meta = ck.restore(
        abstract_like(trainer.state, trainer._state_shardings))
    ck.close()
    assert meta["global_step"] == trainer.global_step
    assert_tree_allclose(state.params, trainer.state.params)
    assert_tree_allclose(state.opt_state, trainer.state.opt_state)


def test_restore_into_different_mesh(tmp_path, seed):
    """Save under (data=2, fsdp=2, tensor=2), restore under
    (data=4, tensor=2): orbax re-shards straight into the new layout."""
    module = GPTLightningModule("tiny", dataset_size=32, batch_size=8)
    s1 = SpmdStrategy(rules=gpt_partition_rules(),
                      axis_names=("data", "fsdp", "tensor"),
                      axis_sizes={"fsdp": 2, "tensor": 2})
    t1 = _fit(str(tmp_path / "a"), strategy=s1, module=module)
    ckdir = str(tmp_path / "sharded")
    t1.save_sharded_checkpoint(ckdir)
    t1.wait_for_checkpoints()
    params1 = jax.tree_util.tree_map(np.asarray, t1.state.params)

    module2 = GPTLightningModule("tiny", dataset_size=32, batch_size=8)
    s2 = SpmdStrategy(rules=gpt_partition_rules(),
                      axis_names=("data", "tensor"),
                      axis_sizes={"tensor": 2})
    t2 = _fit(str(tmp_path / "b"), strategy=s2, module=module2,
              max_steps=5, resume=ckdir)
    # resumed at step 3, ran to 5
    assert t2.global_step == 5

    # weights at restore time equaled the saved ones: re-run restore only
    module3 = GPTLightningModule("tiny", dataset_size=32, batch_size=8)
    t3 = _fit(str(tmp_path / "c"), strategy=s2, module=module3,
              max_steps=3, resume=ckdir)  # max_steps == saved step: no new steps
    assert_tree_allclose(
        jax.tree_util.tree_map(np.asarray, t3.state.params), params1)


def test_sharded_checkpoint_callback(tmp_path, seed):
    cb = ShardedCheckpoint(dirpath=str(tmp_path / "cks"),
                           every_n_train_steps=2, every_n_epochs=0)
    _fit(str(tmp_path), max_steps=5, callbacks=[cb])
    ck = ShardedCheckpointer(str(tmp_path / "cks"))
    assert ck.all_steps() == [2, 4]
    ck.close()


def test_callback_default_dir_and_epoch_cadence(tmp_path, seed):
    cb = ShardedCheckpoint()  # defaults: every epoch, root-dir subdir
    trainer = Trainer(max_epochs=2, enable_checkpointing=False,
                      num_sanity_val_steps=0, limit_val_batches=0,
                      limit_train_batches=2, log_every_n_steps=1,
                      callbacks=[cb], default_root_dir=str(tmp_path),
                      seed=0)
    trainer.fit(BoringModel())
    ck = ShardedCheckpointer(str(tmp_path / "sharded_checkpoints"))
    assert len(ck.all_steps()) == 2
    ck.close()


def test_same_step_saved_twice_is_noop(tmp_path, seed):
    """Two cadences (every-N-steps + every-epoch) can land on one global
    step; the second save must be a silent no-op, not an orbax
    StepAlreadyExistsError that kills the fit."""
    cb = ShardedCheckpoint(dirpath=str(tmp_path / "cks"),
                           every_n_train_steps=2)  # epochs default ON too
    trainer = Trainer(max_epochs=1, enable_checkpointing=False,
                      num_sanity_val_steps=0, limit_val_batches=0,
                      limit_train_batches=4, log_every_n_steps=1,
                      callbacks=[cb], default_root_dir=str(tmp_path),
                      seed=0)
    trainer.fit(BoringModel())  # epoch ends at step 4 == a step cadence hit
    ck = ShardedCheckpointer(str(tmp_path / "cks"))
    assert ck.all_steps() == [2, 4]
    ck.close()


def test_restore_specific_step_dir(tmp_path, seed):
    """resume_from_checkpoint may point at one step directory
    (.../cks/<step>), not just the manager root."""
    cb = ShardedCheckpoint(dirpath=str(tmp_path / "cks"),
                           every_n_train_steps=2, every_n_epochs=0)
    _fit(str(tmp_path), max_steps=4, callbacks=[cb])
    step_dir = str(tmp_path / "cks" / "2")
    assert ShardedCheckpointer.is_sharded_checkpoint(step_dir)
    t2 = _fit(str(tmp_path / "b"), max_steps=3, resume=step_dir)
    assert t2.global_step == 3  # resumed at 2, ran one more


def test_resume_at_max_steps_is_inert(tmp_path, seed):
    """Resuming a checkpoint already at max_steps must run zero batches
    and must not drift the epoch counter upward."""
    trainer = _fit(str(tmp_path), max_steps=3)
    ckdir = str(tmp_path / "sharded")
    trainer.save_sharded_checkpoint(ckdir)
    trainer.wait_for_checkpoints()
    saved_epoch = trainer.current_epoch
    params = jax.tree_util.tree_map(np.asarray, trainer.state.params)

    t2 = _fit(str(tmp_path / "b"), max_steps=3, resume=ckdir)
    assert t2.global_step == 3
    assert t2.current_epoch == saved_epoch  # no per-cycle drift
    assert_tree_allclose(
        jax.tree_util.tree_map(np.asarray, t2.state.params), params)


def test_callback_state_roundtrips_through_sharded_meta(tmp_path, seed):
    """EarlyStopping/ModelCheckpoint state must survive a sharded
    save→restore like it does on the msgpack path."""
    from ray_lightning_tpu import EarlyStopping

    es = EarlyStopping(monitor="loss", patience=3, mode="min")
    trainer = _fit(str(tmp_path), max_steps=3, callbacks=[es])
    es._mon.best = 0.123  # make state distinctive
    es.wait_count = 2
    ckdir = str(tmp_path / "sharded")
    trainer.save_sharded_checkpoint(ckdir)
    trainer.wait_for_checkpoints()

    es2 = EarlyStopping(monitor="loss", patience=3, mode="min")
    _fit(str(tmp_path / "b"), max_steps=3, resume=ckdir, callbacks=[es2])
    assert es2._mon.best == pytest.approx(0.123)
    assert es2.wait_count == 2


def test_max_to_keep_evicts_oldest(tmp_path, seed):
    trainer = _fit(str(tmp_path), max_steps=1)
    ck = ShardedCheckpointer(str(tmp_path / "cks"), max_to_keep=2)
    for step in (1, 2, 3):
        ck.save(step, trainer.state, {"global_step": step})
    ck.wait()
    assert ck.all_steps() == [2, 3]   # oldest evicted
    state, meta = ck.restore(
        abstract_like(trainer.state, trainer._state_shardings))
    assert meta["global_step"] == 3
    ck.close()


def test_inflight_save_durable_when_fit_raises(tmp_path, seed):
    """An async save kicked off right before a training exception must
    still land on disk — the fit-loop finally waits on and closes the
    checkpointers even while unwinding."""
    from ray_lightning_tpu.core.callbacks import Callback

    class SaveThenBoom(Callback):
        def on_train_batch_end(self, trainer, module, outputs, batch, idx):
            if trainer.global_step == 2:
                trainer.save_sharded_checkpoint(str(tmp_path / "cks"))
                raise RuntimeError("post-save boom")

    trainer = Trainer(max_epochs=1, enable_checkpointing=False,
                      num_sanity_val_steps=0, limit_val_batches=0,
                      log_every_n_steps=1, callbacks=[SaveThenBoom()],
                      default_root_dir=str(tmp_path), seed=0)
    with pytest.raises(RuntimeError, match="post-save boom"):
        trainer.fit(BoringModel())
    assert trainer._sharded_checkpointers == {}   # closed during unwind
    ck = ShardedCheckpointer(str(tmp_path / "cks"))
    assert ck.all_steps() == [2]                  # save became durable
    ck.close()


def _comm_fit(tmp, batch_size, policy="comm", resume=None, max_steps=3):
    """Single-process comm-plane fit whose mesh data size (== the
    CommState residual world) is set by the batch size (the DDP mesh
    clamps its data axis to the global batch)."""
    trainer = Trainer(
        max_epochs=10, max_steps=max_steps, enable_checkpointing=False,
        num_sanity_val_steps=0, limit_val_batches=0, seed=0,
        log_every_n_steps=1, default_root_dir=tmp,
        comm_policy={"compress": "int8", "axes": ("data",)}
        if policy == "comm" else None,
        resume_from_checkpoint=resume)
    trainer.fit(BoringModel(batch_size=batch_size))
    return trainer


def _np_tree(tree):
    return jax.tree_util.tree_map(np.asarray, tree)


def test_commstate_reshard_2_to_4_rebuckets_residual(tmp_path, seed):
    """N→M restore with both sides carrying the PR 5 error-feedback
    residual: params + inner optimizer state restore exactly; the
    [N, ...] residual re-buckets to [M, ...] by mean-broadcast — exact
    in the injected-correction sum (1/world)·Σᵢ rᵢ, the documented
    tolerance being only the per-rank attribution of the error."""
    from ray_lightning_tpu.comm.collectives import CommState

    t1 = _comm_fit(str(tmp_path / "a"), batch_size=2)   # world 2
    assert isinstance(t1.state.opt_state, CommState)
    res1 = _np_tree(t1.state.opt_state.residual)
    assert jax.tree_util.tree_leaves(res1)[0].shape[0] == 2
    assert any(np.abs(leaf).sum() > 0
               for leaf in jax.tree_util.tree_leaves(res1))
    ckdir = str(tmp_path / "ck")
    t1.save_sharded_checkpoint(ckdir)
    t1.wait_for_checkpoints()

    # max_steps == saved step: restore only, zero new steps
    t2 = _comm_fit(str(tmp_path / "b"), batch_size=4, resume=ckdir)
    res2 = _np_tree(t2.state.opt_state.residual)
    assert jax.tree_util.tree_leaves(res2)[0].shape[0] == 4
    assert_tree_allclose(_np_tree(t1.state.params),
                         _np_tree(t2.state.params), rtol=0, atol=0)
    assert_tree_allclose(_np_tree(t1.state.opt_state.inner),
                         _np_tree(t2.state.opt_state.inner),
                         rtol=0, atol=0)
    for a, b in zip(jax.tree_util.tree_leaves(res1),
                    jax.tree_util.tree_leaves(res2)):
        expect = np.broadcast_to(a.mean(0, keepdims=True), b.shape)
        np.testing.assert_allclose(b, expect, rtol=1e-6)
        # the invariant the re-bucket preserves exactly
        np.testing.assert_allclose(b.sum(0) / b.shape[0],
                                   a.sum(0) / a.shape[0], rtol=1e-6)


def test_commstate_reshard_2_to_1_drops_residual(tmp_path, seed):
    """Shrinking to world 1 leaves no compressed axis (the comm plane
    resolves inert), so the saved residual is dropped — params and
    inner optimizer state still restore exactly."""
    from ray_lightning_tpu.comm.collectives import CommState

    t1 = _comm_fit(str(tmp_path / "a"), batch_size=2)
    ckdir = str(tmp_path / "ck")
    t1.save_sharded_checkpoint(ckdir)
    t1.wait_for_checkpoints()

    t2 = _comm_fit(str(tmp_path / "b"), batch_size=1, resume=ckdir)
    assert not isinstance(t2.state.opt_state, CommState)
    assert_tree_allclose(_np_tree(t1.state.params),
                         _np_tree(t2.state.params), rtol=0, atol=0)
    assert_tree_allclose(_np_tree(t1.state.opt_state.inner),
                         _np_tree(t2.state.opt_state), rtol=0, atol=0)


def test_commstate_reshard_1_to_2_keeps_zero_residual(tmp_path, seed):
    """Growing from a comm-less save into a comm-on topology: inner
    state restores exactly and error feedback restarts from the zero
    residual (nothing saved to re-bucket)."""
    from ray_lightning_tpu.comm.collectives import CommState

    t1 = _comm_fit(str(tmp_path / "a"), batch_size=1)   # world 1: inert
    assert not isinstance(t1.state.opt_state, CommState)
    ckdir = str(tmp_path / "ck")
    t1.save_sharded_checkpoint(ckdir)
    t1.wait_for_checkpoints()

    t2 = _comm_fit(str(tmp_path / "b"), batch_size=2, resume=ckdir)
    assert isinstance(t2.state.opt_state, CommState)
    res2 = _np_tree(t2.state.opt_state.residual)
    assert jax.tree_util.tree_leaves(res2)[0].shape[0] == 2
    assert all((leaf == 0).all()
               for leaf in jax.tree_util.tree_leaves(res2))
    assert_tree_allclose(_np_tree(t1.state.params),
                         _np_tree(t2.state.params), rtol=0, atol=0)
    assert_tree_allclose(_np_tree(t1.state.opt_state),
                         _np_tree(t2.state.opt_state.inner),
                         rtol=0, atol=0)


def test_sharded_meta_records_comm_world(tmp_path, seed):
    t1 = _comm_fit(str(tmp_path / "a"), batch_size=2)
    ckdir = str(tmp_path / "ck")
    t1.save_sharded_checkpoint(ckdir)
    t1.wait_for_checkpoints()
    ck = ShardedCheckpointer(ckdir)
    _, meta = ck.restore(
        abstract_like(t1.state, t1._state_shardings))
    ck.close()
    assert meta["comm_world"] == 2


def test_restore_missing_dir_raises(tmp_path):
    ck = ShardedCheckpointer(str(tmp_path / "empty"))
    with pytest.raises(FileNotFoundError):
        ck.restore(None)
    ck.close()


def test_is_sharded_checkpoint_detection(tmp_path):
    assert not ShardedCheckpointer.is_sharded_checkpoint(
        str(tmp_path / "nope"))
    f = tmp_path / "flat.ckpt"
    f.write_bytes(b"x")
    assert not ShardedCheckpointer.is_sharded_checkpoint(str(f))
    d = tmp_path / "cks" / "7"
    d.mkdir(parents=True)
    assert ShardedCheckpointer.is_sharded_checkpoint(str(tmp_path / "cks"))
