"""Run telemetry: span/counter recording, driver-side aggregation,
heartbeat watchdog, and Perfetto trace export (telemetry/).

The e2e case mirrors the subsystem's reason to exist (SURVEY.md §5: the
reference observes nothing but an epoch timer, and only on rank 0): a
2-worker local-backend fit must land step/compile/collective spans from
BOTH ranks on one driver timeline.
"""

import json
import logging
import os
import time

import pytest

from ray_lightning_tpu import Trainer, telemetry
from ray_lightning_tpu.models import BoringModel
from ray_lightning_tpu.telemetry.aggregator import (
    TelemetryAggregator,
    WorkerHeartbeatTimeout,
)
from ray_lightning_tpu.telemetry.heartbeat import make_heartbeat

from tests.utils import cpu_plugin


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Recorder and active aggregator are process/thread-ambient; never
    leak them across tests."""
    yield
    telemetry.disable()
    telemetry.set_active(None)


# -- span/counter API ----------------------------------------------------

def test_span_nesting_depth_and_rank():
    telemetry.enable(rank=3, sink=None, flush_every=None)
    with telemetry.span("outer"):
        with telemetry.span("inner", step=7):
            pass
    recs = telemetry.drain()
    by_name = {r["name"]: r for r in recs}
    assert set(by_name) == {"outer", "inner"}
    assert by_name["inner"]["depth"] == 1
    assert by_name["outer"]["depth"] == 0
    assert by_name["inner"]["attrs"] == {"step": 7}
    assert all(r["rank"] == 3 for r in recs)
    assert all(r["dur"] >= 0 for r in recs)
    # inner is fully contained in outer on the timeline
    assert by_name["inner"]["ts"] >= by_name["outer"]["ts"]


def test_disabled_mode_is_noop_singleton():
    assert not telemetry.enabled()
    # identity: no allocation per call when disabled
    assert telemetry.span("a") is telemetry.span("b")
    telemetry.counter("x", 1.0)      # must not raise
    assert telemetry.drain() == []
    # overhead: purely a bound sanity check (generous: ~20µs/span)
    t0 = time.monotonic()
    for _ in range(10_000):
        with telemetry.span("step"):
            pass
    assert time.monotonic() - t0 < 0.2


def test_counter_and_last_span():
    telemetry.enable(rank=0, sink=None, flush_every=None)
    assert telemetry.last_span() is None
    with telemetry.span("compile"):
        assert telemetry.last_span() == "compile"
        telemetry.counter("hbm_mb", 12.5)
    recs = telemetry.drain()
    counters = [r for r in recs if r["t"] == "counter"]
    (c,) = counters
    assert c["name"] == "hbm_mb" and c["value"] == 12.5


def test_sink_batching_and_flush():
    batches = []
    telemetry.enable(rank=1, sink=batches.append, flush_every=2)
    with telemetry.span("a"):
        pass
    assert batches == []           # below the batch threshold
    with telemetry.span("b"):
        pass
    assert len(batches) == 1 and len(batches[0]) == 2
    with telemetry.span("c"):
        pass
    telemetry.flush()
    assert len(batches) == 2 and batches[1][0]["name"] == "c"


def test_ring_buffer_drops_oldest_never_grows():
    telemetry.enable(rank=0, sink=None, capacity=3, flush_every=None)
    for i in range(10):
        telemetry.counter("c", i)
    assert telemetry.dropped() == 7
    recs = telemetry.drain()
    assert [r["value"] for r in recs] == [7.0, 8.0, 9.0]


def test_failing_sink_never_raises_into_training():
    def bad_sink(batch):
        raise RuntimeError("sink down")

    telemetry.enable(rank=0, sink=bad_sink, flush_every=1)
    with telemetry.span("step"):   # must not raise
        pass
    telemetry.flush()


# -- aggregator ----------------------------------------------------------

def _span_rec(rank, name, ts, dur, **attrs):
    r = {"t": "span", "name": name, "ts": ts, "dur": dur, "rank": rank,
         "depth": 0}
    if attrs:
        r["attrs"] = attrs
    return r


def test_aggregator_merges_ranks_and_exports(tmp_path):
    agg = TelemetryAggregator(str(tmp_path / "telemetry"))
    # rank 1 is a 2x straggler
    for i in range(10):
        agg.maybe_ingest(telemetry.spans_item(
            0, [_span_rec(0, "step", 100.0 + i, 0.010)]))
        agg.maybe_ingest(telemetry.spans_item(
            1, [_span_rec(1, "step", 100.0 + i, 0.020)]))
    agg.ingest_records(0, [_span_rec(0, "compile", 99.0, 1.0)])
    stats = agg.step_stats()
    assert stats["per_rank"]["0"]["steps"] == 10
    assert stats["per_rank"]["1"]["mean_ms"] == pytest.approx(20.0)
    assert stats["straggler_skew"] == pytest.approx(2.0)

    paths = agg.export()
    with open(paths["trace"]) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    span_events = [e for e in events if e.get("ph") == "X"]
    assert {e["pid"] for e in span_events} == {0, 1}
    assert {"step", "compile"} <= {e["name"] for e in span_events}
    with open(paths["jsonl"]) as f:
        lines = [json.loads(line) for line in f]
    assert lines[-1]["t"] == "summary"
    assert lines[-1]["step_stats"]["straggler_skew"] == pytest.approx(2.0)
    assert {r.get("rank") for r in lines[:-1]} == {0, 1}


def test_aggregator_normalizes_chunked_steps(tmp_path):
    agg = TelemetryAggregator(str(tmp_path))
    # one span covering k=4 steps in 40ms -> 10ms/step
    agg.ingest_records(0, [_span_rec(0, "step", 10.0, 0.040, k=4)])
    assert agg.step_stats()["per_rank"]["0"]["mean_ms"] == \
        pytest.approx(10.0)


def test_non_telemetry_items_pass_through(tmp_path):
    agg = TelemetryAggregator(str(tmp_path))
    assert not agg.maybe_ingest({"some": "dict"})
    assert not agg.maybe_ingest((0, lambda: None))
    assert not agg.maybe_ingest("string")


def test_watchdog_names_silent_rank(tmp_path, caplog):
    clock = [0.0]
    agg = TelemetryAggregator(str(tmp_path), heartbeat_timeout=5.0,
                              clock=lambda: clock[0])
    agg.maybe_ingest(make_heartbeat(0))
    beat1 = make_heartbeat(1)
    beat1["pid"] = beat1["pid"] + 1   # distinct worker process
    beat1["last_span"] = "step"
    agg.maybe_ingest(beat1)
    clock[0] = 3.0
    agg.maybe_ingest(make_heartbeat(0))   # rank 0 keeps beating
    clock[0] = 7.0
    agg.maybe_ingest(make_heartbeat(0))
    with caplog.at_level(logging.WARNING,
                         logger="ray_lightning_tpu.telemetry.aggregator"):
        agg.watchdog_check()
    msgs = [r.message for r in caplog.records]
    assert any("rank 1" in m and "last span 'step'" in m for m in msgs)
    assert not any("rank 0:" in m for m in msgs)
    # warned once, not per poll iteration
    caplog.clear()
    with caplog.at_level(logging.WARNING,
                         logger="ray_lightning_tpu.telemetry.aggregator"):
        agg.watchdog_check()
    assert not caplog.records


def test_watchdog_hard_timeout_raises(tmp_path):
    clock = [0.0]
    agg = TelemetryAggregator(str(tmp_path), heartbeat_timeout=1.0,
                              hard_timeout=5.0, clock=lambda: clock[0])
    agg.maybe_ingest(make_heartbeat(2))
    clock[0] = 6.0
    with pytest.raises(WorkerHeartbeatTimeout, match="rank 2"):
        agg.watchdog_check()


# -- trainer integration -------------------------------------------------

def test_local_fit_exports_trace(tmp_path, seed):
    trainer = Trainer(max_epochs=1, limit_train_batches=4,
                      limit_val_batches=2, num_sanity_val_steps=0,
                      enable_checkpointing=True, seed=0,
                      log_every_n_steps=1, default_root_dir=str(tmp_path),
                      telemetry=True)
    trainer.fit(BoringModel())
    paths = trainer._telemetry_paths
    assert paths is not None
    with open(paths["trace"]) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]
             if e.get("ph") == "X"}
    assert {"step", "compile", "init", "data_wait", "eval",
            "checkpoint"} <= names
    assert paths["summary"]["step_stats"]["per_rank"]["0"]["steps"] == 4
    # recorder must be torn down after the run
    assert not telemetry.enabled()
    assert telemetry.get_active() is None


def test_telemetry_disabled_records_nothing(tmp_path, seed):
    trainer = Trainer(max_epochs=1, limit_train_batches=2,
                      limit_val_batches=0, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      default_root_dir=str(tmp_path))
    trainer.fit(BoringModel())
    assert trainer._telemetry_paths is None
    assert not os.path.exists(os.path.join(str(tmp_path), "telemetry"))


def test_config_resolution():
    from ray_lightning_tpu.telemetry import TelemetryConfig
    assert not TelemetryConfig.resolve(None).enabled
    assert TelemetryConfig.resolve(True).enabled
    cfg = TelemetryConfig.resolve({"heartbeat_timeout": 7.5})
    assert cfg.enabled and cfg.heartbeat_timeout == 7.5
    assert TelemetryConfig.resolve(cfg) is cfg
    with pytest.raises(TypeError):
        TelemetryConfig.resolve(3)
    assert cfg.resolve_dir("/root/x") == "/root/x/telemetry"


def test_per_trial_dir_resolution(tmp_path):
    """Inside a builtin tune trial, telemetry lands in the trial's own
    logdir (tune/runner.py Trial.telemetry_dir contract)."""
    from ray_lightning_tpu.telemetry import TelemetryConfig
    from ray_lightning_tpu.tune.runner import Trial
    from ray_lightning_tpu.tune.session import TrialSession, set_session
    trial = Trial("trial_00000", {}, str(tmp_path / "trial_00000"))
    set_session(TrialSession(trial, lambda *a: None))
    try:
        cfg = TelemetryConfig.resolve(True)
        assert cfg.resolve_dir("/elsewhere") == trial.telemetry_dir
    finally:
        set_session(None)


# -- end-to-end over the cluster backend --------------------------------

@pytest.mark.slow
def test_e2e_two_workers_spans_from_both_ranks(tmp_path, seed):
    """2-worker local-backend fit: the driver aggregator must see
    step/compile/collective spans from BOTH ranks and export a
    Perfetto-loadable trace.json."""
    trainer = Trainer(max_epochs=1, limit_train_batches=4,
                      limit_val_batches=0, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      log_every_n_steps=1, plugins=[cpu_plugin(2)],
                      default_root_dir=str(tmp_path),
                      telemetry={"heartbeat_interval": 0.5})
    trainer.fit(BoringModel())

    paths = trainer._telemetry_paths
    assert paths is not None
    with open(paths["trace"]) as f:
        trace = json.load(f)          # valid JSON by construction
    span_events = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    by_rank = {}
    for e in span_events:
        by_rank.setdefault(e["pid"], set()).add(e["name"])
    assert set(by_rank) == {0, 1}
    for rank, names in by_rank.items():
        assert {"step", "compile", "collective"} <= names, \
            f"rank {rank} missing spans: {names}"

    with open(paths["jsonl"]) as f:
        lines = [json.loads(line) for line in f]
    summary = lines[-1]
    assert summary["t"] == "summary"
    per_rank = summary["step_stats"]["per_rank"]
    assert set(per_rank) == {"0", "1"}
    assert per_rank["0"]["steps"] == 4 and per_rank["1"]["steps"] == 4
    # both workers heartbeat over the queue channel
    hb = trainer.plugin._telemetry_agg.heartbeats()
    assert {v["beat"]["rank"] for v in hb.values()} == {0, 1}
