"""Run telemetry: span/counter recording, driver-side aggregation,
heartbeat watchdog, and Perfetto trace export (telemetry/).

The e2e case mirrors the subsystem's reason to exist (SURVEY.md §5: the
reference observes nothing but an epoch timer, and only on rank 0): a
2-worker local-backend fit must land step/compile/collective spans from
BOTH ranks on one driver timeline.
"""

import json
import logging
import os
import time

import pytest

from ray_lightning_tpu import Trainer, telemetry
from ray_lightning_tpu.models import BoringModel
from ray_lightning_tpu.telemetry import tracing
from ray_lightning_tpu.telemetry.aggregator import (
    TelemetryAggregator,
    WorkerHeartbeatTimeout,
)
from ray_lightning_tpu.telemetry.flight import FlightRecorder
from ray_lightning_tpu.telemetry.heartbeat import make_heartbeat

from tests.utils import cpu_plugin


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Recorder and active aggregator are process/thread-ambient; never
    leak them across tests."""
    yield
    telemetry.disable()
    telemetry.disable_anatomy()
    telemetry.disable_metrics()
    telemetry.set_active(None)


# -- span/counter API ----------------------------------------------------

def test_span_nesting_depth_and_rank():
    telemetry.enable(rank=3, sink=None, flush_every=None)
    with telemetry.span("outer"):
        with telemetry.span("inner", step=7):
            pass
    recs = telemetry.drain()
    by_name = {r["name"]: r for r in recs}
    assert set(by_name) == {"outer", "inner"}
    assert by_name["inner"]["depth"] == 1
    assert by_name["outer"]["depth"] == 0
    assert by_name["inner"]["attrs"] == {"step": 7}
    assert all(r["rank"] == 3 for r in recs)
    assert all(r["dur"] >= 0 for r in recs)
    # inner is fully contained in outer on the timeline
    assert by_name["inner"]["ts"] >= by_name["outer"]["ts"]


def test_disabled_mode_is_noop_singleton():
    assert not telemetry.enabled()
    # identity: no allocation per call when disabled
    assert telemetry.span("a") is telemetry.span("b")
    telemetry.counter("x", 1.0)      # must not raise
    assert telemetry.drain() == []
    # overhead: purely a bound sanity check (generous: ~20µs/span)
    t0 = time.monotonic()
    for _ in range(10_000):
        with telemetry.span("step"):
            pass
    assert time.monotonic() - t0 < 0.2


def test_counter_and_last_span():
    telemetry.enable(rank=0, sink=None, flush_every=None)
    assert telemetry.last_span() is None
    with telemetry.span("compile"):
        assert telemetry.last_span() == "compile"
        telemetry.counter("hbm_mb", 12.5)
    recs = telemetry.drain()
    counters = [r for r in recs if r["t"] == "counter"]
    (c,) = counters
    assert c["name"] == "hbm_mb" and c["value"] == 12.5


def test_sink_batching_and_flush():
    batches = []
    telemetry.enable(rank=1, sink=batches.append, flush_every=2)
    with telemetry.span("a"):
        pass
    assert batches == []           # below the batch threshold
    with telemetry.span("b"):
        pass
    assert len(batches) == 1 and len(batches[0]) == 2
    with telemetry.span("c"):
        pass
    telemetry.flush()
    assert len(batches) == 2 and batches[1][0]["name"] == "c"


def test_ring_buffer_drops_oldest_never_grows():
    telemetry.enable(rank=0, sink=None, capacity=3, flush_every=None)
    for i in range(10):
        telemetry.counter("c", i)
    assert telemetry.dropped() == 7
    recs = telemetry.drain()
    assert [r["value"] for r in recs] == [7.0, 8.0, 9.0]


def test_failing_sink_never_raises_into_training():
    def bad_sink(batch):
        raise RuntimeError("sink down")

    telemetry.enable(rank=0, sink=bad_sink, flush_every=1)
    with telemetry.span("step"):   # must not raise
        pass
    telemetry.flush()


# -- aggregator ----------------------------------------------------------

def _span_rec(rank, name, ts, dur, **attrs):
    r = {"t": "span", "name": name, "ts": ts, "dur": dur, "rank": rank,
         "depth": 0}
    if attrs:
        r["attrs"] = attrs
    return r


def test_aggregator_merges_ranks_and_exports(tmp_path):
    agg = TelemetryAggregator(str(tmp_path / "telemetry"))
    # rank 1 is a 2x straggler
    for i in range(10):
        agg.maybe_ingest(telemetry.spans_item(
            0, [_span_rec(0, "step", 100.0 + i, 0.010)]))
        agg.maybe_ingest(telemetry.spans_item(
            1, [_span_rec(1, "step", 100.0 + i, 0.020)]))
    agg.ingest_records(0, [_span_rec(0, "compile", 99.0, 1.0)])
    stats = agg.step_stats()
    assert stats["per_rank"]["0"]["steps"] == 10
    assert stats["per_rank"]["1"]["mean_ms"] == pytest.approx(20.0)
    assert stats["straggler_skew"] == pytest.approx(2.0)

    paths = agg.export()
    with open(paths["trace"]) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    span_events = [e for e in events if e.get("ph") == "X"]
    assert {e["pid"] for e in span_events} == {0, 1}
    assert {"step", "compile"} <= {e["name"] for e in span_events}
    with open(paths["jsonl"]) as f:
        lines = [json.loads(line) for line in f]
    assert lines[-1]["t"] == "summary"
    assert lines[-1]["step_stats"]["straggler_skew"] == pytest.approx(2.0)
    assert {r.get("rank") for r in lines[:-1]} == {0, 1}


def test_aggregator_normalizes_chunked_steps(tmp_path):
    agg = TelemetryAggregator(str(tmp_path))
    # one span covering k=4 steps in 40ms -> 10ms/step
    agg.ingest_records(0, [_span_rec(0, "step", 10.0, 0.040, k=4)])
    assert agg.step_stats()["per_rank"]["0"]["mean_ms"] == \
        pytest.approx(10.0)


def test_non_telemetry_items_pass_through(tmp_path):
    agg = TelemetryAggregator(str(tmp_path))
    assert not agg.maybe_ingest({"some": "dict"})
    assert not agg.maybe_ingest((0, lambda: None))
    assert not agg.maybe_ingest("string")


def test_watchdog_names_silent_rank(tmp_path, caplog):
    clock = [0.0]
    agg = TelemetryAggregator(str(tmp_path), heartbeat_timeout=5.0,
                              clock=lambda: clock[0])
    agg.maybe_ingest(make_heartbeat(0))
    beat1 = make_heartbeat(1)
    beat1["pid"] = beat1["pid"] + 1   # distinct worker process
    beat1["last_span"] = "step"
    agg.maybe_ingest(beat1)
    clock[0] = 3.0
    agg.maybe_ingest(make_heartbeat(0))   # rank 0 keeps beating
    clock[0] = 7.0
    agg.maybe_ingest(make_heartbeat(0))
    with caplog.at_level(logging.WARNING,
                         logger="ray_lightning_tpu.telemetry.aggregator"):
        agg.watchdog_check()
    msgs = [r.message for r in caplog.records]
    assert any("rank 1" in m and "last span 'step'" in m for m in msgs)
    assert not any("rank 0:" in m for m in msgs)
    # warned once, not per poll iteration
    caplog.clear()
    with caplog.at_level(logging.WARNING,
                         logger="ray_lightning_tpu.telemetry.aggregator"):
        agg.watchdog_check()
    assert not caplog.records


def test_watchdog_hard_timeout_raises(tmp_path):
    clock = [0.0]
    agg = TelemetryAggregator(str(tmp_path), heartbeat_timeout=1.0,
                              hard_timeout=5.0, clock=lambda: clock[0])
    agg.maybe_ingest(make_heartbeat(2))
    clock[0] = 6.0
    with pytest.raises(WorkerHeartbeatTimeout, match="rank 2"):
        agg.watchdog_check()


# -- per-request tracing (telemetry/tracing.py) --------------------------

def test_trace_id_round_trip_driver_worker_aggregator(tmp_path):
    """THE trace-propagation round-trip: a driver-side request span and
    worker-side spans carrying the same trace id (the single ``trace``
    attr and the decode's ``traces`` fan-out map) reassemble into ONE
    time-ordered tree in the aggregator — exactly the id flow of a
    serve request (driver plan broadcast -> worker span batch -> queue
    -> aggregator)."""
    agg = TelemetryAggregator(str(tmp_path))
    telemetry.set_active(agg)
    # worker-side recorder whose sink delivers like the queue channel
    telemetry.enable(
        rank=0,
        sink=lambda recs: agg.maybe_ingest(telemetry.spans_item(0, recs)),
        flush_every=1)
    tid = tracing.mint_trace_id()
    sibling = tracing.mint_trace_id()
    t0 = time.time()
    # driver: queue-wait phase (thread-ambient active aggregator)
    tracing.record_request_span("queue_wait", t0 - 0.3, t0 - 0.1,
                                trace=tid, tenant="alice", req=0)
    # worker: per-bucket prefill + one shared decode over two requests
    with telemetry.span("prefill", trace=tid, bucket=16, slot=2):
        pass
    with telemetry.span("decode", traces={2: tid, 3: sibling}, slots=2):
        pass
    # driver: completion summary span carrying the attribution
    tracing.record_request_span("request", t0 - 0.3, t0 + 0.2,
                                trace=tid, tenant="alice", status="ok",
                                tokens=8, queue_s=0.2, ttft_s=0.25,
                                tpot_s=0.03)
    trees = agg.request_trees()
    assert set(trees) == {tid, sibling}
    names = [r["name"] for r in trees[tid]]
    assert names[0] in ("queue_wait", "request")     # same start ts
    assert set(names) == {"queue_wait", "request", "prefill", "decode"}
    # one tree spans BOTH sides of the queue channel
    assert {r["rank"] for r in trees[tid]} == {-1, 0}
    # the shared decode span fans out to the sibling's tree too
    assert [r["name"] for r in trees[sibling]] == ["decode"]
    # and the per-tenant breakdown attributes the phases
    bd = agg.tenant_breakdown()["alice"]
    assert bd["requests"] == 1 and bd["tokens"] == 8
    assert bd["queue_wait_p50_ms"] == pytest.approx(200.0, abs=1.0)
    assert bd["ttft_p50_ms"] == pytest.approx(250.0, abs=1.0)
    assert bd["decode_p50_ms"] == pytest.approx(250.0, abs=1.0)
    assert bd["prefill_p50_ms"] is not None
    # the exported summary carries the trace-plane section
    paths = agg.export()
    assert paths["summary"]["requests"]["traced"] == 2
    assert "alice" in paths["summary"]["requests"]["tenants"]


def test_tenant_breakdown_counts_failed_requests(tmp_path):
    agg = TelemetryAggregator(str(tmp_path))
    t0 = time.time()
    agg.ingest_records(-1, [
        tracing.span_record("request", t0, t0 + 1.0, trace="aaaa",
                            tenant="bob", status="ok", tokens=4,
                            ttft_s=0.5, queue_s=0.1),
        tracing.span_record("request", t0, t0 + 2.0, trace="bbbb",
                            tenant="bob", status="failed", tokens=0,
                            ttft_s=2.0, queue_s=2.0)])
    bd = agg.tenant_breakdown()["bob"]
    assert bd["requests"] == 2 and bd["failed"] == 1
    # failed requests participate in the percentiles (optimism fix)
    assert bd["ttft_p99_ms"] == pytest.approx(2000.0, abs=1.0)


# -- crash flight recorder (telemetry/flight.py) -------------------------

def test_flight_recorder_bounded_and_dumps(tmp_path):
    fr = FlightRecorder(str(tmp_path), span_capacity=8, beat_capacity=3)
    for i in range(100):
        fr.note_records(2, [{"t": "span", "name": f"step{i}",
                             "ts": float(i), "dur": 0.01, "rank": 2}])
        fr.note_heartbeat({"rank": 2, "pid": 1, "wall": float(i),
                           "last_span": f"step{i}", "dropped": 0})
    # bounded-size invariant: rings never exceed capacity
    assert len(fr._records[2]) == 8 and len(fr._beats[2]) == 3
    path = fr.dump(2, "unit-test cause")
    assert path == str(tmp_path / "flight_2.json")
    doc = json.load(open(path))
    assert doc["rank"] == 2 and doc["cause"] == "unit-test cause"
    assert doc["last_span"] == "step99"      # newest records survive
    assert len(doc["spans"]) == 8
    assert doc["heartbeats"][-1]["last_span"] == "step99"
    assert fr.dumped[2] == path


def test_aggregator_mirrors_into_flight_and_watchdog_dumps(tmp_path):
    """A wedge verdict dumps the rank's black box: the watchdog's first
    warning for a silent rank writes flight_<rank>.json with its last
    spans and heartbeat trail."""
    clock = [0.0]
    agg = TelemetryAggregator(str(tmp_path), heartbeat_timeout=5.0,
                              clock=lambda: clock[0])
    agg.ingest_records(1, [{"t": "span", "name": "step", "ts": 100.0,
                            "dur": 0.02, "rank": 1, "depth": 0}])
    beat = make_heartbeat(1)
    agg.maybe_ingest(beat)
    clock[0] = 10.0
    agg.watchdog_check()
    path = tmp_path / "flight_1.json"
    assert path.exists()
    doc = json.load(open(path))
    assert doc["rank"] == 1
    assert "wedge" in doc["cause"]
    assert doc["last_span"] == "step"
    assert doc["heartbeats"], "heartbeat trail missing from black box"


# -- on-demand profiling (POST /debug/profile) ---------------------------

def test_debug_profile_endpoint_and_status(tmp_path):
    """POST /debug/profile arms a window on the controller; /status
    links its state and, with the serve pump's hooks driven, the
    resulting dir."""
    import urllib.request
    from ray_lightning_tpu.telemetry import exporter as _exporter
    from ray_lightning_tpu.telemetry.tracing import ServeProfileController

    agg = TelemetryAggregator(str(tmp_path))
    ctl = ServeProfileController(str(tmp_path))
    server = _exporter.MetricsHTTPServer(agg, port=0,
                                         profile_controller=ctl).start()
    try:
        req = urllib.request.Request(
            server.url + "/debug/profile?steps=2", method="POST")
        with urllib.request.urlopen(req, timeout=5) as r:
            resp = json.loads(r.read())
        assert resp["accepted"] and resp["steps"] == 2
        # a second POST while armed is rejected with 409
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                urllib.request.Request(
                    server.url + "/debug/profile?steps=1",
                    method="POST"), timeout=5)
        assert exc.value.code == 409
        # drive the pump hooks: claim the window, count its steps
        pending = ctl.take_pending()
        assert pending["id"] == resp["id"]
        ctl.note_step()
        ctl.note_step()
        with urllib.request.urlopen(server.url + "/status",
                                    timeout=5) as r:
            status = json.loads(r.read())
        assert status["profile"]["state"] == "done"
        assert status["profile"]["last_dir"] == resp["dir"]
    finally:
        server.stop()


def test_debug_profile_without_controller_is_501(tmp_path):
    import urllib.request
    from ray_lightning_tpu.telemetry import exporter as _exporter
    agg = TelemetryAggregator(str(tmp_path))
    server = _exporter.MetricsHTTPServer(agg, port=0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                urllib.request.Request(
                    server.url + "/debug/profile?steps=1",
                    method="POST"), timeout=5)
        assert exc.value.code == 501
    finally:
        server.stop()


def test_fit_profile_control_file_round_trip(tmp_path, monkeypatch):
    """The fit path's arm: FileProfileController writes the control
    file, the loop-engine poller (profile_tick) picks it up from the
    env, captures a real jax.profiler window, and drops the rank done
    marker /status reports."""
    control = str(tmp_path / "profile" / "control.json")
    ctl = tracing.FileProfileController(control)
    assert ctl.status() == {"state": "idle"}
    resp = ctl.request(1)
    assert resp["accepted"] and os.path.exists(control)
    monkeypatch.setenv(tracing.PROFILE_CONTROL_ENV, control)
    monkeypatch.setenv("RLT_PROCESS_ID", "0")
    tracing.reset_profile_tick()
    try:
        tracing.profile_tick()       # polls the file, starts the trace
        tracing.profile_tick()       # counts the step, stops + marks
        status = ctl.status()
        assert status["state"] == "done", status
        assert status["ranks_done"] == ["rank0"]
        trace_dir = os.path.join(resp["dir"], "rank0")
        found = [os.path.join(dp, f) for dp, _, fs in os.walk(trace_dir)
                 for f in fs]
        assert found, "profiler window wrote no trace files"
    finally:
        tracing.reset_profile_tick()


# -- anatomy plane (telemetry/anatomy.py) --------------------------------

def test_anatomy_parses_real_capture(tmp_path, monkeypatch):
    """A REAL profiler capture (via the fit control-file machinery, the
    same path POST /debug/profile arms) parses into a StepAnatomy whose
    parts are nonnegative and sum to <= the step wall, and the
    controller's status links the parsed anatomy next to last_dir."""
    import jax
    import jax.numpy as jnp
    from ray_lightning_tpu.telemetry import anatomy

    control = str(tmp_path / "profile" / "control.json")
    ctl = tracing.FileProfileController(control)
    resp = ctl.request(3)
    monkeypatch.setenv(tracing.PROFILE_CONTROL_ENV, control)
    monkeypatch.setenv("RLT_PROCESS_ID", "0")
    tracing.reset_profile_tick()
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((64, 64))
    f(x).block_until_ready()
    try:
        tracing.profile_tick()       # polls the file, starts the trace
        for _ in range(3):           # real device work INSIDE the window
            f(x).block_until_ready()
            tracing.profile_tick()
    finally:
        tracing.reset_profile_tick()
    status = ctl.status()
    assert status["state"] == "done", status

    a = anatomy.parse_trace_anatomy(os.path.join(resp["dir"], "rank0"))
    assert a.steps >= 1 and a.devices >= 1
    assert a.compute_s >= 0 and a.collective_s >= 0
    assert a.exposed_s >= 0 and a.host_s >= 0
    # the decomposition identity: parts sum to the step wall (tiny
    # epsilon: the compact dict rounds to nanoseconds)
    assert a.compute_s + a.exposed_s + a.host_s <= a.wall_s + 1e-8
    assert a.compute_s > 0, "no compute measured from a real capture"
    # the controller's status links the parsed anatomy per rank
    assert "anatomy" in status, status
    assert status["anatomy"]["0"]["compute_s"] > 0
    # Pallas decode-kernel events land in compute, not comm: the
    # category table files them under pallas/custom and the collective
    # classifier (the one the anatomy parser consults) rejects them.
    from ray_lightning_tpu.comm import audit
    assert anatomy.bucket_of(
        "flash_decode_kernel.12") == "pallas/custom"
    assert anatomy.bucket_of(
        "flash_decode_paged_kernel") == "pallas/custom"
    assert audit.collective_kind("flash_decode_kernel.12") is None
    assert audit.collective_kind(
        "custom-call.flash_decode_paged_kernel") is None
    assert audit.collective_kind("all-gather.7") == "all-gather"


def test_anatomy_golden_overlap_math(tmp_path):
    """The golden synthetic fixture pins the exposed-comm interval
    math: fully-overlapped -> ~0 exposed, serialized -> exposed ≈
    collective; partial overlap measures exactly the uncovered part."""
    from ray_lightning_tpu.telemetry import anatomy

    serial = tmp_path / "serial"
    anatomy.write_synthetic_trace(str(serial), ops=[
        {"name": "fusion.1", "ts": 0, "dur": 10_000},
        {"name": "all-reduce.1", "ts": 10_000, "dur": 4_000},
    ], modules=[{"name": "jit_step", "ts": 0, "dur": 14_000}])
    a = anatomy.parse_trace_anatomy(str(serial), steps=1, ici_size=1,
                                    multi_process=False)
    assert a.exposed_s == pytest.approx(0.004)
    assert a.collective_s == pytest.approx(0.004)
    assert a.collective_by_op == {"all-reduce": pytest.approx(0.004)}
    assert a.collective_by_link == {"ici": pytest.approx(0.004)}
    assert a.wall_s == pytest.approx(
        a.compute_s + a.exposed_s + a.host_s)

    overlapped = tmp_path / "overlapped"
    anatomy.write_synthetic_trace(str(overlapped), ops=[
        {"name": "fusion.1", "ts": 0, "dur": 10_000},
        {"name": "all-reduce.1", "ts": 2_000, "dur": 4_000},
    ])
    a = anatomy.parse_trace_anatomy(str(overlapped), steps=1, ici_size=1,
                                    multi_process=True)
    assert a.exposed_s == 0.0
    assert a.collective_s == pytest.approx(0.004)
    # group-less collective on a multi-process mesh charges DCN
    assert a.collective_by_link == {"dcn": pytest.approx(0.004)}

    partial = tmp_path / "partial"
    anatomy.write_synthetic_trace(str(partial), ops=[
        {"name": "fusion.1", "ts": 0, "dur": 10_000},
        {"name": "all-reduce.1", "ts": 8_000, "dur": 4_000},
    ])
    a = anatomy.parse_trace_anatomy(str(partial), steps=1, ici_size=1,
                                    multi_process=False)
    assert a.exposed_s == pytest.approx(0.002)   # [10ms, 12ms) uncovered


def test_anatomy_replica_groups_decide_link(tmp_path):
    """A collective event whose args carry the lowered HLO's
    replica_groups is classified by comm/audit.py's crosses_dcn, not
    the topology fallback: groups inside one 2-rank host block -> ici
    even on a multi-process mesh."""
    from ray_lightning_tpu.telemetry import anatomy

    d = tmp_path / "groups"
    anatomy.write_synthetic_trace(str(d), ops=[
        {"name": "fusion.1", "ts": 0, "dur": 5_000},
        {"name": "all-reduce.2", "ts": 5_000, "dur": 1_000,
         "args": {"long_name": "all-reduce(f32[8]), "
                               "replica_groups={{0,1},{2,3}}"}},
        {"name": "all-reduce.3", "ts": 6_000, "dur": 2_000,
         "args": {"long_name": "all-reduce(f32[8]), "
                               "replica_groups={{0,2},{1,3}}"}},
    ])
    a = anatomy.parse_trace_anatomy(str(d), steps=1, ici_size=2,
                                    multi_process=True)
    assert a.collective_by_link["ici"] == pytest.approx(0.001)
    assert a.collective_by_link["dcn"] == pytest.approx(0.002)


def test_anatomy_ingest_status_flight_and_export(tmp_path):
    """Anatomy wire items land on the aggregator: /status gains the
    per-rank section with straggler skew, the export summary carries
    it, and a flight dump names where the rank's device time went."""
    from ray_lightning_tpu.telemetry import anatomy
    from ray_lightning_tpu.telemetry import exporter as _exporter

    agg = TelemetryAggregator(str(tmp_path))
    a0 = {"steps": 2, "devices": 1, "wall_s": 0.010, "compute_s": 0.006,
          "collective_s": 0.004, "exposed_s": 0.003, "host_s": 0.001,
          "collective_by_op": {"all-reduce": 0.004},
          "collective_by_link": {"dcn": 0.004},
          "bubble_fraction": 0.1, "modules": {}, "source": "cpu-host"}
    a1 = dict(a0, wall_s=0.020)      # rank 1 is a 2x straggler
    assert agg.maybe_ingest(anatomy.anatomy_item(0, a0))
    assert agg.maybe_ingest(anatomy.anatomy_item(1, a1))
    stats = agg.anatomy_stats()
    assert set(stats["per_rank"]) == {"0", "1"}
    assert stats["windows"] == 2
    assert stats["straggler_skew"] == pytest.approx(2.0)
    doc = _exporter.render_status(agg)
    assert doc["anatomy"]["per_rank"]["1"]["wall_s"] == 0.020
    paths = agg.export()
    assert paths["summary"]["anatomy"]["straggler_skew"] == \
        pytest.approx(2.0)
    dump = agg.flight.dump(1, "unit-test cause")
    assert json.load(open(dump))["anatomy"]["wall_s"] == 0.020


def test_anatomy_controller_cadence_and_gauges(tmp_path):
    """The auto-capture controller: every_n dispatches arm a window
    through the WorkerProfiler machinery, the rank parses its OWN
    capture, ships only the compact dict, and publishes the
    rlt_anatomy_* gauges + the measured exposed-comm source label."""
    import jax
    import jax.numpy as jnp
    from ray_lightning_tpu.telemetry import anatomy
    from ray_lightning_tpu.telemetry import metrics as _metrics

    reg = _metrics.enable_metrics(rank=0, sink=None, pump=False)
    shipped = []
    ctl = telemetry.enable_anatomy(rank=0, every_n=2, window=2,
                                   sink=shipped.append)
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((32, 32))
    f(x).block_until_ready()
    for _ in range(6):               # ticks 2..4 arm + close one window
        telemetry.anatomy_tick()
        f(x).block_until_ready()
    assert ctl.windows >= 1, "no anatomy window completed"
    assert shipped, "anatomy dict was not shipped"
    item = shipped[0]
    assert item["kind"] == "anatomy" and item["rank"] == 0
    a = item["anatomy"]
    assert a["compute_s"] > 0
    assert a["compute_s"] + a["exposed_s"] + a["host_s"] \
        <= a["wall_s"] + 1e-8
    # teardown abandons the in-flight second window and removes its
    # capture dir — only compact dicts ever leave the rank
    inflight = ctl._dir
    telemetry.disable_anatomy()
    assert ctl._dir is None
    assert inflight is None or not os.path.isdir(inflight)
    assert reg.gauge("rlt_anatomy_compute_seconds").value() == \
        pytest.approx(a["compute_s"])
    assert reg.counter("rlt_anatomy_windows_total").value() >= 1
    # measured exposed feeds the comm gauge under the anatomy source
    assert reg.gauge("rlt_comm_exposed_seconds").value(
        source="anatomy") == pytest.approx(a["exposed_s"])


def test_anatomy_config_env_roundtrip(monkeypatch):
    from ray_lightning_tpu.telemetry import TelemetryConfig, anatomy

    for var in (anatomy.ANATOMY_ENV, anatomy.ANATOMY_EVERY_ENV,
                anatomy.ANATOMY_STEPS_ENV):
        monkeypatch.delenv(var, raising=False)
    assert TelemetryConfig().resolved_anatomy()[0] is None
    assert TelemetryConfig().worker_env() == {}
    cfg = TelemetryConfig(anatomy_every_n_steps=10, anatomy_steps=3)
    assert cfg.resolved_anatomy() == (10, 3)
    env = cfg.worker_env()
    assert env == {anatomy.ANATOMY_EVERY_ENV: "10",
                   anatomy.ANATOMY_STEPS_ENV: "3"}
    # a worker's default config resolves the same cadence from the env
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    assert TelemetryConfig().resolved_anatomy() == (10, 3)
    monkeypatch.delenv(anatomy.ANATOMY_EVERY_ENV)
    monkeypatch.delenv(anatomy.ANATOMY_STEPS_ENV)
    monkeypatch.setenv(anatomy.ANATOMY_ENV, "1")
    assert TelemetryConfig().resolved_anatomy() == \
        (anatomy.DEFAULT_EVERY_N, anatomy.DEFAULT_WINDOW)


def test_local_fit_with_anatomy_armed(tmp_path, seed):
    """An in-process fit with the cadence armed lands a measured
    per-rank anatomy in the exported summary."""
    trainer = Trainer(max_epochs=1, limit_train_batches=8,
                      limit_val_batches=0, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      log_every_n_steps=1, default_root_dir=str(tmp_path),
                      telemetry={"anatomy_every_n_steps": 2,
                                 "anatomy_steps": 2})
    trainer.fit(BoringModel())
    summary = trainer._telemetry_paths["summary"]
    assert "anatomy" in summary, "no anatomy in export summary"
    a = summary["anatomy"]["per_rank"]["0"]
    assert a["compute_s"] >= 0 and a["exposed_s"] >= 0
    assert a["compute_s"] + a["exposed_s"] + a["host_s"] \
        <= a["wall_s"] + 1e-8
    # controller torn down with the rest of telemetry
    assert telemetry.get_anatomy_controller() is None


# -- trainer integration -------------------------------------------------

def test_local_fit_exports_trace(tmp_path, seed):
    trainer = Trainer(max_epochs=1, limit_train_batches=4,
                      limit_val_batches=2, num_sanity_val_steps=0,
                      enable_checkpointing=True, seed=0,
                      log_every_n_steps=1, default_root_dir=str(tmp_path),
                      telemetry=True)
    trainer.fit(BoringModel())
    paths = trainer._telemetry_paths
    assert paths is not None
    with open(paths["trace"]) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]
             if e.get("ph") == "X"}
    assert {"step", "compile", "init", "data_wait", "eval",
            "checkpoint"} <= names
    assert paths["summary"]["step_stats"]["per_rank"]["0"]["steps"] == 4
    # recorder must be torn down after the run
    assert not telemetry.enabled()
    assert telemetry.get_active() is None


def test_telemetry_disabled_records_nothing(tmp_path, seed):
    trainer = Trainer(max_epochs=1, limit_train_batches=2,
                      limit_val_batches=0, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      default_root_dir=str(tmp_path))
    trainer.fit(BoringModel())
    assert trainer._telemetry_paths is None
    assert not os.path.exists(os.path.join(str(tmp_path), "telemetry"))


def test_config_resolution():
    from ray_lightning_tpu.telemetry import TelemetryConfig
    assert not TelemetryConfig.resolve(None).enabled
    assert TelemetryConfig.resolve(True).enabled
    cfg = TelemetryConfig.resolve({"heartbeat_timeout": 7.5})
    assert cfg.enabled and cfg.heartbeat_timeout == 7.5
    assert TelemetryConfig.resolve(cfg) is cfg
    with pytest.raises(TypeError):
        TelemetryConfig.resolve(3)
    assert cfg.resolve_dir("/root/x") == "/root/x/telemetry"


def test_per_trial_dir_resolution(tmp_path):
    """Inside a builtin tune trial, telemetry lands in the trial's own
    logdir (tune/runner.py Trial.telemetry_dir contract)."""
    from ray_lightning_tpu.telemetry import TelemetryConfig
    from ray_lightning_tpu.tune.runner import Trial
    from ray_lightning_tpu.tune.session import TrialSession, set_session
    trial = Trial("trial_00000", {}, str(tmp_path / "trial_00000"))
    set_session(TrialSession(trial, lambda *a: None))
    try:
        cfg = TelemetryConfig.resolve(True)
        assert cfg.resolve_dir("/elsewhere") == trial.telemetry_dir
    finally:
        set_session(None)


# -- end-to-end over the cluster backend --------------------------------

@pytest.mark.slow
def test_e2e_two_workers_spans_from_both_ranks(tmp_path, seed):
    """2-worker local-backend fit: the driver aggregator must see
    step/compile/collective spans from BOTH ranks and export a
    Perfetto-loadable trace.json."""
    trainer = Trainer(max_epochs=1, limit_train_batches=4,
                      limit_val_batches=0, num_sanity_val_steps=0,
                      enable_checkpointing=False, seed=0,
                      log_every_n_steps=1, plugins=[cpu_plugin(2)],
                      default_root_dir=str(tmp_path),
                      telemetry={"heartbeat_interval": 0.5,
                                 "anatomy_every_n_steps": 2,
                                 "anatomy_steps": 2})
    trainer.fit(BoringModel())

    # anatomy acceptance: with the cadence armed, BOTH ranks parsed a
    # real capture locally and the driver's summary carries per-rank
    # measured step anatomy (the same dict /status serves live)
    anatomy = trainer._telemetry_paths["summary"].get("anatomy")
    assert anatomy and set(anatomy["per_rank"]) == {"0", "1"}, anatomy
    for rank, a in anatomy["per_rank"].items():
        assert a["compute_s"] >= 0 and a["exposed_s"] >= 0
        assert a["compute_s"] + a["exposed_s"] + a["host_s"] \
            <= a["wall_s"] + 1e-8, (rank, a)
        # the 2-process data axis all-reduce is measured and, being
        # group-less across hosts, charged to the DCN link
        assert "all-reduce" in a["collective_by_op"], (rank, a)
        assert a["collective_by_link"].get("dcn", 0) > 0, (rank, a)

    paths = trainer._telemetry_paths
    assert paths is not None
    with open(paths["trace"]) as f:
        trace = json.load(f)          # valid JSON by construction
    span_events = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    by_rank = {}
    for e in span_events:
        by_rank.setdefault(e["pid"], set()).add(e["name"])
    assert set(by_rank) == {0, 1}
    for rank, names in by_rank.items():
        assert {"step", "compile", "collective"} <= names, \
            f"rank {rank} missing spans: {names}"

    with open(paths["jsonl"]) as f:
        lines = [json.loads(line) for line in f]
    summary = lines[-1]
    assert summary["t"] == "summary"
    per_rank = summary["step_stats"]["per_rank"]
    assert set(per_rank) == {"0", "1"}
    assert per_rank["0"]["steps"] == 4 and per_rank["1"]["steps"] == 4
    # both workers heartbeat over the queue channel
    hb = trainer.plugin._telemetry_agg.heartbeats()
    assert {v["beat"]["rank"] for v in hb.values()} == {0, 1}
