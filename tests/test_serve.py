"""Serving plane (ray_lightning_tpu/serve/): buckets, scheduler
invariants, prefill/decode numerics, slot insert/evict, and the
2-worker continuous-batching e2e with a live /metrics scrape.

The e2e mirrors the acceptance bar: a 2-worker CPU-mesh serve run must
complete prompts from >=2 tenants through continuous batching with ZERO
decode-loop retraces after warmup (trace + compile-cache hit counters
prove it), and the driver's /metrics must serve TTFT and
tokens-per-second live while requests are in flight.
"""

import json
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from ray_lightning_tpu import Server, telemetry
from ray_lightning_tpu.models.gpt import GPTConfig, GPTLightningModule
from ray_lightning_tpu.parallel.strategy import DataParallelStrategy
from ray_lightning_tpu.serve.buckets import (
    bucket_for,
    pad_to_bucket,
    resolve_buckets,
)
from ray_lightning_tpu.serve.engine import ServeEngine
from ray_lightning_tpu.serve.kvcache import KVCacheSpec, SlotAllocator
from ray_lightning_tpu.serve.scheduler import Scheduler
from ray_lightning_tpu.serve.worker import ServeWorker


@pytest.fixture(autouse=True)
def _clean_metrics():
    yield
    telemetry.disable_metrics()
    telemetry.set_active(None)


# -- buckets ---------------------------------------------------------------

def test_bucket_resolution_and_selection():
    bs = resolve_buckets(None, 300)
    assert bs[-1] == 300 and list(bs) == sorted(set(bs))
    assert resolve_buckets((64, 16), 64) == (16, 64)     # sorted, deduped
    assert bucket_for(1, bs) == bs[0]
    assert bucket_for(16, (16, 64)) == 16                # boundary: exact
    assert bucket_for(17, (16, 64)) == 64
    with pytest.raises(ValueError, match="exceeds"):
        bucket_for(65, (16, 64))
    with pytest.raises(ValueError, match="exceeds the model context"):
        resolve_buckets((128,), 64)


def test_pad_to_bucket_shape_and_content():
    out = pad_to_bucket([3, 1, 4], 8, pad_id=0)
    assert out.shape == (1, 8) and out.dtype == np.int32
    assert out[0].tolist() == [3, 1, 4, 0, 0, 0, 0, 0]
    assert pad_to_bucket(np.arange(8), 8).shape == (1, 8)  # exact fit
    with pytest.raises(ValueError):
        pad_to_bucket(np.arange(9), 8)


def test_slot_allocator_insert_evict():
    alloc = SlotAllocator(3)
    s0, s1, s2 = alloc.acquire(), alloc.acquire(), alloc.acquire()
    assert {s0, s1, s2} == {0, 1, 2} and alloc.acquire() is None
    alloc.release(s1)
    assert alloc.acquire() == s1          # freed slot is reusable
    with pytest.raises(ValueError):
        alloc.release(99)


def test_kv_cache_spec_geometry():
    class _Aval:
        shape = (1, 16, 4, 32)
    spec = KVCacheSpec.from_capture([_Aval(), _Aval()], slots=8,
                                    max_seq_len=64)
    assert spec.shape == (2, 8, 64, 4, 32)
    assert spec.nbytes(2) == 2 * 2 * 8 * 64 * 4 * 32 * 2


# -- scheduler: fairness, quota, slot uniqueness, drain-ability ------------

def _fake_step(sched):
    """Run one plan against a fabricated fleet result."""
    plan = sched.plan()
    if plan is None:
        return None
    live = sched.allocator.in_use()
    assert len(live) == len(set(live)) <= sched.allocator.slots
    result = {"prefill": {p["slot"]: 7 for p in plan["prefills"]},
              "decode": {}}
    if plan["decode"] is not None:
        result["decode"] = {s: 9 for s in plan["decode"]["slots"]}
    sched.apply(plan, result)
    return plan


def test_scheduler_tenant_quota_enforced():
    sched = Scheduler(buckets=(8,), slots=4, max_seq_len=16,
                      quotas={"greedy": 1}, max_prefills_per_step=4,
                      default_max_new_tokens=3)
    reqs = [sched.submit([1, 2, 3], tenant="greedy") for _ in range(5)]
    for _ in range(100):
        if sched.idle():
            break
        assert sched.stats()["per_tenant"].get(
            "greedy", {}).get("active", 0) <= 1
        _fake_step(sched)
    assert all(r.done() for r in reqs)


def test_scheduler_fair_share_interleaves_tenants():
    """A tenant with a deep backlog must not starve a later tenant: the
    fair-share key admits the quiet tenant's request before the chatty
    one's queue is drained."""
    sched = Scheduler(buckets=(8,), slots=2, max_seq_len=16,
                      max_prefills_per_step=1, default_max_new_tokens=4)
    chatty = [sched.submit([1, 2], tenant="chatty") for _ in range(6)]
    quiet = sched.submit([1, 2], tenant="quiet")
    admitted_quiet_at = None
    for step in range(200):
        if sched.idle():
            break
        _fake_step(sched)
        if admitted_quiet_at is None and quiet.state != "queued":
            admitted_quiet_at = step
    assert quiet.done() and all(r.done() for r in chatty)
    # quiet got a slot while chatty requests were still queued
    assert admitted_quiet_at is not None and admitted_quiet_at <= 2


def test_scheduler_caps_new_tokens_to_context():
    sched = Scheduler(buckets=(8,), slots=1, max_seq_len=8,
                      default_max_new_tokens=100)
    req = sched.submit(np.arange(1, 7))     # prompt len 6, context 8
    # precise cap: the final produced token never writes K/V
    assert req.max_new_tokens == 8 - 6 + 1


def test_scheduler_eos_stops_generation():
    sched = Scheduler(buckets=(8,), slots=1, max_seq_len=32,
                      default_max_new_tokens=10, eos_token=9)
    req = sched.submit([1, 2, 3])
    _fake_step(sched)                       # prefill -> token 7
    _fake_step(sched)                       # decode  -> token 9 == eos
    assert req.done() and req.result(1).tolist() == [7, 9]


def test_scheduler_fail_all_unblocks_waiters():
    sched = Scheduler(buckets=(8,), slots=1, max_seq_len=32)
    queued = sched.submit([1, 2])
    _fake_step(sched)   # admit it
    boom = RuntimeError("fleet died")
    sched.fail_all(boom)
    with pytest.raises(RuntimeError, match="fleet died"):
        queued.result(1)


# -- engine: numerics + slot isolation (in-process, 8-device CPU mesh) -----

TINY = GPTConfig(vocab_size=128, block_size=32, n_layer=2, n_head=2,
                 n_embd=32, remat=False)


@pytest.fixture(scope="module")
def engine():
    module = GPTLightningModule(TINY)
    eng = ServeEngine(module, DataParallelStrategy(), buckets=(8, 16),
                      slots=4, max_seq_len=TINY.block_size,
                      seed=0).setup()
    return eng


def _generate(eng, slot, prompt, n):
    """Drive one request through prefill + n-1 decode steps, other
    slots idle."""
    toks = [eng.prefill(slot, pad_to_bucket(prompt, 8), len(prompt), 8)]
    t = np.zeros(eng.slots, np.int32)
    p = np.zeros(eng.slots, np.int32)
    pos = len(prompt)
    for _ in range(n - 1):
        t[slot], p[slot] = toks[-1], pos
        toks.append(int(eng.decode(t, p)[slot]))
        pos += 1
    return toks


def _reference(eng, prompt, n):
    """Greedy continuation via the WHOLE-SEQUENCE forward on the same
    params (the numerics-equality oracle)."""
    model = eng.module.configure_decode_model()
    params = jax.device_get(eng.params)
    seq = list(np.asarray(prompt))
    out = []
    for _ in range(n):
        logits = model.apply({"params": params},
                             np.asarray([seq], np.int32), True)
        out.append(int(np.argmax(np.asarray(logits)[0, -1])))
        seq.append(out[-1])
    return out


def test_prefill_decode_matches_whole_sequence_forward(engine):
    """Greedy continuation through the KV-cache path equals the
    whole-sequence forward token-for-token, and the decode logits match
    the full forward's within the documented bf16 tolerance (2e-2,
    same bar as the comm plane's bf16 parity legs)."""
    prompt = np.array([5, 9, 2, 7, 11, 3, 1], np.int32)
    got = _generate(engine, 1, prompt, 6)
    want = _reference(engine, prompt, 6)
    assert got == want, (got, want)

    # logits-level check at an interior decode position
    model = engine.module.configure_decode_model()
    params = jax.device_get(engine.params)
    seq = list(prompt) + want[:3]
    full = np.asarray(model.apply(
        {"params": params}, np.asarray([seq], np.int32), True))[0, -1]
    # replay through a fresh cache to the same position
    eng_logits = _decode_logits(engine, prompt, want[:3])
    np.testing.assert_allclose(eng_logits, full, atol=2e-2, rtol=2e-2)


def _decode_logits(eng, prompt, generated):
    """Raw decode-step logits after replaying ``generated`` into a
    scratch cache (slot 0) via the model's decode method."""
    model = eng.module.configure_decode_model()
    params = jax.device_get(eng.params)
    spec = eng.kv_spec
    S = spec.slots
    kh = np.zeros(spec.shape, np.float32)
    vh = np.zeros(spec.shape, np.float32)
    # prefill capture via the normal forward
    padded = pad_to_bucket(prompt, 8)
    _, cap = model.apply({"params": params}, padded, True,
                         mutable=["kv_cache"])
    from ray_lightning_tpu.core.steps import kv_layer_pairs
    for i, (ck, cv) in enumerate(kv_layer_pairs(cap["kv_cache"])):
        kh[i, 0, :8] = np.asarray(ck[0], np.float32)
        vh[i, 0, :8] = np.asarray(cv[0], np.float32)
    k = jax.numpy.asarray(kh, jax.numpy.bfloat16)
    v = jax.numpy.asarray(vh, jax.numpy.bfloat16)
    toks = [int(x) for x in generated]
    pos = len(prompt)
    logits = None
    for i, cur in enumerate(toks):
        t = np.zeros((S,), np.int32)
        p = np.zeros((S,), np.int32)
        t[0], p[0] = cur, pos + i
        logits, k, v = model.apply({"params": params}, t, p, k, v,
                                   method="decode")
    return np.asarray(logits)[0]


def test_slot_insert_evict_does_not_disturb_neighbors(engine):
    """Continuous batching correctness: a request decoded WHILE another
    is inserted/evicted in a neighboring slot produces the identical
    tokens as the same request run alone."""
    eng = engine
    a = np.array([4, 8, 15, 16, 23], np.int32)
    b = np.array([42, 3, 7], np.int32)
    c = np.array([2, 2, 6, 10], np.int32)
    alone = _generate(eng, 0, a, 6)

    # interleaved: a in slot 0, b joins slot 1 mid-flight, b finishes
    # (evicted), c reuses slot 1 — a's tokens must not change
    toks_a = [eng.prefill(0, pad_to_bucket(a, 8), len(a), 8)]
    pos_a = len(a)
    t = np.zeros(eng.slots, np.int32)
    p = np.zeros(eng.slots, np.int32)

    def step(slots):
        for s, (tok, pos) in slots.items():
            t[s], p[s] = tok, pos
        return eng.decode(t, p)

    out = step({0: (toks_a[-1], pos_a)})
    toks_a.append(int(out[0]))
    toks_b = [eng.prefill(1, pad_to_bucket(b, 8), len(b), 8)]
    pos_b = len(b)
    for i in range(2):
        out = step({0: (toks_a[-1], pos_a + 1 + i),
                    1: (toks_b[-1], pos_b + i)})
        toks_a.append(int(out[0]))
        toks_b.append(int(out[1]))
    # b evicted; c reuses slot 1 (prefill overwrites the prefix)
    toks_c = [eng.prefill(1, pad_to_bucket(c, 8), len(c), 8)]
    pos_c = len(c)
    for i in range(2):
        out = step({0: (toks_a[-1], pos_a + 3 + i),
                    1: (toks_c[-1], pos_c + i)})
        toks_a.append(int(out[0]))
        toks_c.append(int(out[1]))
    assert toks_a == alone, (toks_a, alone)
    # and the inserted requests match their own solo runs
    assert toks_b == _reference(eng, b, 3)
    assert toks_c == _reference(eng, c, 3)


def _assert_greedy_parity(eng, prompt, got, atol=2e-2):
    """Token-level parity with the whole-sequence greedy reference,
    teacher-forced on the engine's own output: at every step the
    generated token must be the reference argmax, or — when jit fusion
    flips a bf16 near-tie — carry a reference logit within the
    documented tolerance (2e-2, the logits bar above) of that argmax.
    Corrupted K/V (e.g. a clobbered position-0 cache entry) moves
    logits far beyond the tolerance, so this still fails hard on real
    cache bugs while staying deterministic across compiled layouts."""
    model = eng.module.configure_decode_model()
    params = jax.device_get(eng.params)
    seq = [int(t) for t in np.asarray(prompt)]
    for i, tok in enumerate(got):
        logits = np.asarray(model.apply(
            {"params": params}, np.asarray([seq], np.int32), True))[0, -1]
        best = int(np.argmax(logits))
        assert tok == best or logits[tok] >= logits[best] - atol, \
            (i, seq, tok, best, float(logits[tok]), float(logits[best]))
        seq.append(int(tok))


def test_serve_step_token_parity_under_concurrent_admissions(engine):
    """The REAL Scheduler driving the REAL ``ServeWorker.serve_step``
    (the production dispatch order), with plans that mix an admitting
    prefill and a decode in the SAME step — the continuous-batching
    shape where a wrong dispatch order lets the decode program's dummy
    position-0 write clobber a just-prefilled slot's K/V (worker.py
    serve_step docstring).  Every request's tokens must equal the
    whole-sequence greedy reference."""
    sched = Scheduler(buckets=engine.buckets, slots=engine.slots,
                      max_seq_len=engine.max_seq_len,
                      max_prefills_per_step=1, default_max_new_tokens=6)
    worker = ServeWorker()
    worker._engine = engine
    worker._rank = 0
    prompts = [np.arange(1, 4 + (i % 5)) for i in range(5)]
    prompts.append(np.arange(2, 13))          # length 11 -> bucket 16
    reqs = [sched.submit(p, tenant=("alice", "bob")[i % 2])
            for i, p in enumerate(prompts)]
    mixed_steps = 0
    for _ in range(200):
        plan = sched.plan()
        if plan is None:
            break
        if plan["prefills"] and plan["decode"] is not None:
            mixed_steps += 1
        sched.apply(plan, worker.serve_step(plan))
    # 6 requests over 4 slots with max_prefills_per_step=1 MUST have
    # admitted into live decodes, or this test isn't testing the bug
    assert mixed_steps >= 2, mixed_steps
    assert all(r.done() for r in reqs)
    for r in reqs:
        _assert_greedy_parity(engine, r.tokens, r.result(1).tolist())


def test_engine_zero_retraces_across_slots_lengths_buckets(engine):
    """Every (bucket, topology) program traces ONCE ever: serving
    different slots, lengths and buckets reuses the warm programs."""
    eng = engine
    before = dict(eng.trace_counts)
    _generate(eng, 3, np.array([9, 1], np.int32), 3)         # bucket 8
    eng.prefill(2, pad_to_bucket(np.arange(1, 12), 16), 11, 16)
    assert eng.trace_counts == before
    assert all(v == 1 for v in eng.trace_counts.values()), \
        eng.trace_counts


@pytest.mark.parametrize("impl", ["flash_decode", "paged"])
def test_engine_kernel_decode_parity_and_zero_retrace(impl, monkeypatch):
    """RLT_DECODE_IMPL forces the Pallas decode kernel (interpret mode
    on CPU): greedy outputs match the dense engine token-for-token, the
    page table rides as a closure constant (not a traced arg) so every
    program still traces ONCE ever, and stats() reports which kernel
    serves the hot path."""
    from ray_lightning_tpu.serve.fleet.pages import PageConfig
    monkeypatch.setenv("RLT_DECODE_IMPL", impl)
    paged = PageConfig(enabled=True, page_size=8) if impl == "paged" \
        else None
    module = GPTLightningModule(TINY)
    eng = ServeEngine(module, DataParallelStrategy(), buckets=(8,),
                      slots=4, max_seq_len=TINY.block_size,
                      seed=0, paged=paged).setup()
    assert eng.stats()["decode_kernel"] == impl
    prompt = np.array([5, 9, 2, 7, 11, 3, 1], np.int32)
    got = _generate(eng, 1, prompt, 6)
    monkeypatch.setenv("RLT_DECODE_IMPL", "dense")
    dense = ServeEngine(GPTLightningModule(TINY), DataParallelStrategy(),
                        buckets=(8,), slots=4,
                        max_seq_len=TINY.block_size, seed=0,
                        paged=paged).setup()
    assert dense.stats()["decode_kernel"] == "dense"
    assert got == _generate(dense, 1, prompt, 6), impl
    # zero retraces: more decode traffic on other slots reuses programs
    before = dict(eng.trace_counts)
    _generate(eng, 3, np.array([9, 1], np.int32), 3)
    assert eng.trace_counts == before, impl


# -- 2-worker e2e: the acceptance run --------------------------------------

def test_e2e_two_workers_multi_tenant_live_metrics(tmp_path, seed,
                                                   engine):
    """2-worker CPU-mesh fleet, 2 tenants through continuous batching:
    every generation matches the whole-sequence greedy reference
    token-for-token, zero decode retraces after warmup (trace counters
    + compile-cache hits prove the compiled-once story), live /metrics
    serves TTFT/tokens-per-second WHILE requests are in flight, and
    graceful drain completes everything."""
    module = GPTLightningModule(TINY)
    server = Server(
        module, num_workers=2, platform="cpu",
        buckets=(8, 16), max_batch_slots=4, max_new_tokens=8,
        tenant_quotas={"alice": 2},
        default_root_dir=str(tmp_path),
        compile_cache=str(tmp_path / "compile_cache"),
        telemetry={"metrics_port": 0, "metrics_interval": 0.2,
                   "heartbeat_interval": 0.5})
    scrape = {}

    def scraper():
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            url = server.metrics_url
            if url is None:
                time.sleep(0.05)
                continue
            try:
                with urllib.request.urlopen(url + "/metrics",
                                            timeout=2) as r:
                    body = r.read().decode()
            except Exception:
                time.sleep(0.05)
                continue
            if "rlt_serve_ttft_seconds_count" in body \
                    and "rlt_serve_tokens_total" in body \
                    and server.scheduler.active_count > 0:
                scrape["body"] = body
                return
            time.sleep(0.02)

    t = threading.Thread(target=scraper, daemon=True)
    t.start()
    try:
        server.start()
        reqs = [server.submit(np.arange(1, 4 + (i % 5)), tenant=tenant)
                for i, tenant in enumerate(
                    ["alice", "bob", "alice", "bob", "alice", "bob"])]
        outs = [r.result(timeout=180) for r in reqs]
        t.join(timeout=60)

        # token-level parity with the whole-sequence reference while
        # tenants were genuinely concurrent (6 requests over 4 slots:
        # admissions land inside live decode steps, the plan shape the
        # serve_step dispatch order exists for).  The fixture engine
        # shares the fleet's params: same config, seed, strategy and
        # smallest bucket -> identical seeded init.
        for r, out in zip(reqs, outs):
            assert len(out) == 8 and r.ttft_s is not None
            _assert_greedy_parity(engine, r.tokens, out.tolist())
        sched = server.scheduler.stats()
        assert sched["completed"] == 6
        assert sched["per_tenant"]["alice"]["served_tokens"] == 24
        assert sched["per_tenant"]["bob"]["served_tokens"] == 24
        assert 0 < sched["batch_occupancy"] <= 1.0

        # -- live scrape landed while requests were in flight
        assert "body" in scrape, "never scraped serve metrics live"
        assert 'rlt_serve_tokens_total{rank="-1",tenant="alice"}' \
            in scrape["body"]
        assert "rlt_serve_ttft_seconds_bucket" in scrape["body"]
        # worker-side engine counters flush on the metrics pump
        # interval; poll a post-completion scrape for them
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with urllib.request.urlopen(server.metrics_url + "/metrics",
                                        timeout=2) as r:
                body = r.read().decode()
            if "rlt_serve_decode_seconds_total" in body \
                    and 'rlt_serve_traces_total{program="decode",rank="1"}' \
                    in body:
                break
            time.sleep(0.1)
        assert "rlt_serve_decode_seconds_total" in body
        assert "rlt_serve_prefill_seconds_total" in body

        # -- zero retraces after warmup, on every worker
        stats = server.stats()
        cold_secs = []
        for w in stats["workers"]:
            assert all(v == 0 for v in w["retraces"].values()), w
            assert w["compile_cache"]["active"]
            cold_secs.append(w["compile_cache"]["backend_compile_secs"])

        # -- trace plane: every request's span tree reassembles
        # (queue_wait -> per-bucket prefill -> decode steps -> request)
        # from driver + worker spans joined by the trace id the plan
        # broadcast propagated.  Worker batches flush at heartbeat
        # cadence (0.5s here); poll briefly for the last ones.
        agg = server._agg
        deadline = time.monotonic() + 30
        trees = {}
        want = {r.trace for r in reqs}
        while time.monotonic() < deadline:
            trees = agg.request_trees()
            if all(
                {"queue_wait", "prefill", "decode", "request"}
                <= {s["name"] for s in trees.get(r.trace, ())}
                    for r in reqs):
                break
            time.sleep(0.1)
        assert want <= set(trees), "not every request traced"
        for r in reqs:
            tree = trees[r.trace]
            names = [s["name"] for s in tree]
            assert {"queue_wait", "prefill", "decode", "request"} \
                <= set(names), f"request {r.id} tree incomplete: {names}"
            # worker spans from the fleet AND driver spans in one tree
            assert {-1} < {s["rank"] for s in tree}
            # decode steps fan out: 8 new tokens = 7 decode advances
            assert sum(1 for n in names if n == "decode") >= 7
            prefills = [s for s in tree if s["name"] == "prefill"]
            assert prefills[0]["attrs"]["bucket"] == r.bucket
        # per-tenant TTFT breakdown (queue vs prefill vs decode) on
        # /status — the trace plane's live summary surface
        with urllib.request.urlopen(server.metrics_url + "/status",
                                    timeout=5) as resp:
            status = json.loads(resp.read())
        for tenant in ("alice", "bob"):
            bd = status["tenants"][tenant]
            assert bd["requests"] == 3 and bd["failed"] == 0
            for key in ("queue_wait_p50_ms", "ttft_p50_ms",
                        "prefill_p50_ms", "decode_p50_ms",
                        "tpot_p50_ms"):
                assert bd[key] is not None and bd[key] >= 0, (key, bd)
        assert status["traced_requests"] >= 6

        # -- on-demand profiling: POST /debug/profile arms a window on
        # the next plan broadcast; every rank captures a non-empty
        # jax.profiler trace dir linked from /status
        post = urllib.request.Request(
            server.metrics_url + "/debug/profile?steps=2",
            method="POST")
        with urllib.request.urlopen(post, timeout=5) as resp:
            armed = json.loads(resp.read())
        assert armed["accepted"], armed
        prof_reqs = [server.submit(np.arange(1, 5), tenant="alice")
                     for _ in range(2)]
        for r in prof_reqs:
            r.result(timeout=180)
        deadline = time.monotonic() + 30
        prof = server.profile_status()
        while time.monotonic() < deadline \
                and prof.get("state") != "done":
            time.sleep(0.1)
            prof = server.profile_status()
        assert prof["state"] == "done", prof
        with urllib.request.urlopen(server.metrics_url + "/status",
                                    timeout=5) as resp:
            assert json.loads(resp.read())["profile"]["last_dir"] \
                == armed["dir"]
        import os
        for rank in (0, 1):
            rank_dir = os.path.join(armed["dir"], f"rank{rank}")
            found = [f for dp, _, fs in os.walk(rank_dir) for f in fs]
            assert found, f"rank {rank} profiler capture is empty"

        # -- graceful drain: no new work admitted, in-flight finishes
        tail = server.submit(np.arange(1, 6), tenant="alice")
        server.drain(timeout=120)
        assert tail.done() and len(tail.result(1)) == 8
        with pytest.raises(RuntimeError, match="draining"):
            server.submit([1, 2, 3])
    finally:
        server.shutdown()
    assert server.telemetry_paths and "metrics" in server.telemetry_paths

    # -- goodput plane (telemetry/goodput.py): the pump's finalized
    # wall partition closes exactly on a REAL serve run — decode
    # (useful, token-producing) vs prefill-only dispatch vs queue
    # idling, with the live /status twin carried by stats()
    from ray_lightning_tpu.telemetry.goodput import check_identity
    gp = server.goodput()
    assert gp is not None and gp["kind"] == "serve"
    assert check_identity(gp), gp
    assert gp["buckets"]["decode"] > 0
    assert gp["buckets"]["prefill"] > 0
    assert gp["buckets"]["queue_idle"] > 0
    assert gp["steps"] > 0 and 0 < gp["goodput_fraction"] < 1
    assert stats["goodput"]["kind"] == "serve"

    # -- compiled once per fleet, ever: a RESTARTED fleet on the same
    # cache dir warm-starts from the first fleet's disk entries —
    # compile-cache hit counters prove it.  Upstream jax only writes
    # entries from process 0 and keys are rank-dependent off-GPU
    # (jax/_src/compiler.py _cache_write / cache_key.py), so the
    # warm-start evidence lives on the rank-0 worker; the zero-retrace
    # property above is per-rank and jax-independent.
    server2 = Server(
        module, num_workers=2, platform="cpu",
        buckets=(8, 16), max_batch_slots=4, max_new_tokens=4,
        default_root_dir=str(tmp_path / "restart"),
        compile_cache=str(tmp_path / "compile_cache"))
    try:
        server2.start()
        out = server2.generate(np.arange(1, 5), timeout=120)
        assert len(out) == 4
        cc = server2.stats()["workers"][0]["compile_cache"]
        assert cc["active"] and cc["hits"] > 0, cc
        # warm rank-0 compile work is a fraction of its cold run's
        assert cc["backend_compile_secs"] < 0.5 * max(cold_secs), \
            (cc, cold_secs)
    finally:
        server2.shutdown()


def _spec_round(sched, slot, draft, verify):
    """One fabricated speculative round: k draft tokens + k+1 verify
    tokens for ``slot``, applied through the real fold."""
    plan = sched.plan()
    assert plan["decode"]["spec"] is True, plan["decode"]
    sched.apply(plan, {"prefill": {}, "decode": {
        slot: {"draft": list(draft), "verify": list(verify)}}})


def test_spec_scheduler_ragged_fold_and_fallback():
    """Speculative-decode fold invariants against fabricated
    draft/verify results (no jax work): the accounting identity
    ``emitted == accepted + corrected`` across ragged acceptance
    (accept-k, accept-0, mid-prefix), max_new truncation mid-round,
    and the rolling-window acceptance floor falling back to plain
    decode for the request's remaining life."""
    from ray_lightning_tpu.serve.spec import SpecConfig
    spec = SpecConfig(enabled=True, k=3, window=4, min_accept=0.5)
    sched = Scheduler(buckets=(8, 16), slots=2, max_seq_len=32,
                      default_max_new_tokens=7, spec=spec)
    req = sched.submit(np.arange(1, 5))
    plan = sched.plan()
    assert plan["prefills"] and plan["prefills"][0]["draft"], plan
    slot = plan["prefills"][0]["slot"]
    sched.apply(plan, {"prefill": {slot: 7}, "decode": {}})
    _spec_round(sched, slot, [10, 11, 12], [10, 11, 12, 13])  # accept-k
    _spec_round(sched, slot, [20, 21, 22], [30, 31, 32, 33])  # accept-0
    _spec_round(sched, slot, [40, 41, 42], [40, 50, 51, 52])  # mid-prefix
    # 7 tokens total -> max_new reached mid-round (truncation leg)
    assert req.done() and list(req.generated) == \
        [7, 10, 11, 12, 13, 30, 40], list(req.generated)
    s = sched.stats()["spec"]
    assert s["emitted"] == s["accepted"] + s["corrected"] == 6, s
    assert (s["accepted"], s["corrected"], s["drafted"]) == (4, 2, 9), s
    assert s["slot_steps"] == 3 and s["tokens_per_target_forward"] == 2.0

    # acceptance collapse: two all-reject rounds fill half the window
    # below min_accept -> spec off for this request, verify[:1] only
    req2 = sched.submit(np.arange(1, 5))
    plan = sched.plan()
    slot = plan["prefills"][0]["slot"]
    sched.apply(plan, {"prefill": {slot: 7}, "decode": {}})
    for i in range(2):
        assert not req2.spec_off, i
        _spec_round(sched, slot, [60 + i, 61, 62], [70 + i, 71, 72, 73])
    assert req2.spec_off, "acceptance floor did not trip"
    assert sched.stats()["spec"]["fallbacks"] == 1
    plan = sched.plan()
    assert plan["decode"].get("spec") is not True, plan["decode"]


def test_spec_server_greedy_parity_across_draft_depths(tmp_path, seed,
                                                       engine):
    """Full-stack speculative decoding on a real 1-worker Server:
    outputs must equal the plain server's token-for-token REGARDLESS
    of draft quality — parity is by construction of the verify fold,
    acceptance only moves throughput.  Three legs share one compile
    cache: plain (reference), a full-clone draft (draft == target, so
    every drafted token verifies: acceptance 1.0, zero fallbacks), and
    a layer-truncated int8-resident draft (the deployment shape, plus
    the draft-weight HBM saving in stats)."""
    module = GPTLightningModule(TINY)
    prompts = [np.arange(1, 4 + (i % 5)) for i in range(4)]

    def run(tag, spec):
        server = Server(
            module, num_workers=1, platform="cpu", buckets=(8, 16),
            max_batch_slots=4, max_new_tokens=8,
            default_root_dir=str(tmp_path / tag),
            compile_cache=str(tmp_path / "compile_cache"),
            telemetry=False, spec=spec)
        try:
            server.start()
            reqs = [server.submit(p, tenant="alice") for p in prompts]
            outs = [r.result(timeout=180).tolist() for r in reqs]
            stats = server.stats()
        finally:
            server.shutdown()
        return outs, stats

    plain, _ = run("plain", None)
    for out, prompt in zip(plain, prompts):
        _assert_greedy_parity(engine, prompt, out)

    clone, cstats = run("clone", {"k": 3, "draft_layers": TINY.n_layer})
    assert clone == plain, "full-clone spec decode broke greedy parity"
    sp = cstats["scheduler"]["spec"]
    # identical weights, but the draft's unrolled program and the
    # batched verify forward fuse differently — bf16 near-ties can
    # flip an argmax between them, so acceptance is high, not 1.0
    # (and the fold corrects every flip: parity above stays exact)
    assert sp["acceptance_rate"] >= 0.8 and sp["fallbacks"] == 0, sp
    assert sp["emitted"] == sp["accepted"] + sp["corrected"], sp
    assert sp["tokens_per_target_forward"] > 2.0, sp

    trunc, tstats = run("int8", {"k": 3, "draft_layers": 1,
                                 "min_accept": 0.05,
                                 "draft_quant": "int8"})
    assert trunc == plain, "truncated-draft spec broke greedy parity"
    sp = tstats["scheduler"]["spec"]
    assert sp["emitted"] == sp["accepted"] + sp["corrected"], sp
    assert sp["tokens_per_target_forward"] >= 1.0, sp
    for w in tstats["workers"]:
        assert all(v == 0 for v in w["retraces"].values()), w
        # int8 residency: the draft copy costs LESS HBM than a
        # dedicated bf16 draft would
        assert w["spec"]["draft_hbm_delta_bytes"] < 0, w["spec"]


def test_server_weights_roundtrip_from_trained_module(tmp_path, seed):
    """The train->serve weights handoff: an engine built from restored
    weights (module._trained_variables / checkpoint state-dict shape)
    serves exactly those params, normalized onto the model's own tree
    structure."""
    module = GPTLightningModule(TINY)
    eng_fresh = ServeEngine(module, DataParallelStrategy(), buckets=(8,),
                            slots=2, max_seq_len=32, seed=0).setup()
    params = jax.device_get(eng_fresh.params)
    bumped = jax.tree_util.tree_map(
        lambda a: (np.asarray(a, np.float32) + 0.05).astype(a.dtype),
        params)
    module._trained_variables = {"params": bumped, "model_state": {}}
    eng_restored = ServeEngine(
        module, DataParallelStrategy(), buckets=(8,), slots=2,
        max_seq_len=32, weights={"params": bumped}).setup()
    got = jax.device_get(eng_restored.params)
    leaves_a = jax.tree_util.tree_leaves(got)
    leaves_b = jax.tree_util.tree_leaves(bumped)
    assert len(leaves_a) == len(leaves_b)
    for a, b in zip(leaves_a, leaves_b):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), atol=1e-6)
    # and the restored engine actually generates with those weights
    prompt = np.array([3, 1, 4, 1, 5], np.int32)
    assert len(_generate(eng_restored, 0, prompt, 3)) == 3
