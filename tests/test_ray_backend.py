"""RayBackend wiring tests against a stub ray module (Ray itself is not
installed in the CI image; what matters here is the mapping onto Ray's
API surface — actor options with TPU custom resources, runtime_env
plumbing, ray.put passthrough, queue lifecycle — the exact call sites
the reference binds at ray_ddp.py:174-180, :331, :335-338, :384)."""

import sys
import types

import pytest


class _FakeActorId:
    def hex(self):
        return "deadbeef"


class _FakeRef:
    """Hashable ObjectRef stand-in (real ObjectRefs hash by id)."""

    def __init__(self, actor, name, args, kwargs):
        self.actor = actor
        self.name = name
        self.args = args
        self.kwargs = kwargs

    def resolve(self):
        return getattr(self.actor.instance, self.name)(
            *self.args, **self.kwargs)


class _FakeMethod:
    def __init__(self, actor, name):
        self._actor = actor
        self._name = name

    def remote(self, *args, **kwargs):
        return _FakeRef(self._actor, self._name, args, kwargs)


class _FakeActor:
    _actor_id = _FakeActorId()

    def __init__(self, cls, args, kwargs, options):
        self.cls = cls
        self.args = args
        self.kwargs = kwargs
        self.options_used = options
        self.instance = cls(*args, **kwargs)
        self.killed = False

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _FakeMethod(self, name)


class _FakeRemoteClass:
    def __init__(self, cls):
        self.cls = cls
        self._options = {}

    def options(self, **kw):
        self._options = kw
        return self

    def remote(self, *args, **kwargs):
        return _FakeActor(self.cls, args, kwargs, self._options)


def _install_stub_ray(monkeypatch):
    ray = types.ModuleType("ray")
    state = {"objects": {}, "killed": [], "inited": False}

    ray.is_initialized = lambda: True
    ray.init = lambda *a, **k: state.__setitem__("inited", True)

    def put(obj):
        oid = f"obj{len(state['objects'])}"
        state["objects"][oid] = obj
        return oid

    def get(ref):
        if isinstance(ref, str) and ref in state["objects"]:
            return state["objects"][ref]
        if isinstance(ref, _FakeRef):
            return ref.resolve()
        return ref

    ray.put = put
    ray.get = get
    # every in-flight ref is immediately ready (stub actors are local)
    ray.wait = lambda refs, num_returns=1, timeout=None: (
        refs[:num_returns], refs[num_returns:])
    ray.remote = lambda cls: _FakeRemoteClass(cls)
    ray.kill = lambda actor, no_restart=False: state["killed"].append(
        (actor, no_restart))
    ray.available_resources = lambda: {"CPU": 8, "TPU": 4}

    ray_util = types.ModuleType("ray.util")
    ray_util_queue = types.ModuleType("ray.util.queue")

    class Empty(Exception):
        pass

    class Queue:
        def __init__(self, actor_options=None):
            self.actor_options = actor_options
            self.items = []
            self.shut = False

        def get_nowait(self):
            if not self.items:
                raise Empty
            return self.items.pop(0)

        def shutdown(self):
            self.shut = True

    ray_util_queue.Queue = Queue
    ray_util_queue.Empty = Empty
    ray_util.queue = ray_util_queue
    ray.util = ray_util

    for name, mod in [("ray", ray), ("ray.util", ray_util),
                      ("ray.util.queue", ray_util_queue)]:
        monkeypatch.setitem(sys.modules, name, mod)
    # the module under test must bind the stub, not a cached real ray
    for mod in ("ray_lightning_tpu.cluster.ray_backend",
                "ray_lightning_tpu.cluster.queue"):
        sys.modules.pop(mod, None)
    return state


@pytest.fixture
def ray_backend(monkeypatch):
    state = _install_stub_ray(monkeypatch)
    from ray_lightning_tpu.cluster.ray_backend import RayBackend
    backend = RayBackend()
    yield backend, state
    sys.modules.pop("ray_lightning_tpu.cluster.ray_backend", None)
    sys.modules.pop("ray_lightning_tpu.cluster.queue", None)


class _Target:
    def __init__(self, base=0):
        self.base = base

    def add(self, x):
        return self.base + x

    def boom(self):
        raise RuntimeError("kapow")


def test_actor_options_map_tpu_resources(ray_backend):
    backend, _ = ray_backend
    handle = backend.create_actor(
        _Target, env={"RLT_X": "1"},
        resources={"CPU": 2, "GPU": 0, "TPU": 4, "extra": 1})
    opts = handle._actor.options_used
    assert opts["num_cpus"] == 2
    assert opts["num_gpus"] == 0
    # TPU chips + custom labels ride the custom-resources dict
    assert opts["resources"] == {"TPU": 4, "extra": 1}
    assert opts["runtime_env"] == {"env_vars": {"RLT_X": "1"}}


def test_actor_call_resolves_and_errors_propagate(ray_backend):
    backend, _ = ray_backend
    handle = backend.create_actor(_Target, 10)
    assert handle._actor.args == (10,)
    assert handle.call("add", 5).result(timeout=10) == 15
    with pytest.raises(RuntimeError, match="kapow"):
        handle.call("boom").result(timeout=10)


def test_kill_uses_no_restart(ray_backend):
    backend, state = ray_backend
    handle = backend.create_actor(_Target)
    handle.kill()
    assert state["killed"] == [(handle._actor, True)]


def test_call_concurrency_is_bounded(ray_backend):
    """128 actors × 4 in-flight calls each resolve through ONE shared
    resolver thread, not a thread per call (VERDICT weak #6)."""
    import threading

    from ray_lightning_tpu.cluster import ray_backend as rb

    backend, _ = ray_backend
    before = threading.active_count()
    handles = [backend.create_actor(_Target, i) for i in range(128)]
    futures = [(h, j, h.call("add", j)) for h in handles for j in range(4)]
    # at most the single resolver thread was added while 512 calls flew
    assert threading.active_count() <= before + 1
    for h, j, fut in futures:
        assert fut.result(timeout=30) == h._actor.args[0] + j
    assert rb._resolver._thread is not None
    assert threading.active_count() <= before + 1


def test_put_get_roundtrip(ray_backend):
    backend, _ = ray_backend
    ref = backend.put({"a": 1})
    assert backend.get(ref) == {"a": 1}


def test_client_address_plumbing(monkeypatch):
    """RAY_ADDRESS / RLT_RAY_ADDRESS reach ray.init — the Ray Client
    (ray://) path the reference exercises in tests/test_client*.py."""
    state = _install_stub_ray(monkeypatch)
    inits = []
    sys.modules["ray"].is_initialized = lambda: False
    sys.modules["ray"].init = lambda *a, **k: inits.append(k) or state
    from ray_lightning_tpu.cluster.ray_backend import RayBackend

    monkeypatch.setenv("RAY_ADDRESS", "ray://head:10001")
    RayBackend()
    assert inits[-1] == {"address": "ray://head:10001"}

    # RLT_RAY_ADDRESS wins over RAY_ADDRESS; explicit arg wins over both
    monkeypatch.setenv("RLT_RAY_ADDRESS", "ray://other:10001")
    RayBackend()
    assert inits[-1] == {"address": "ray://other:10001"}
    RayBackend(address="ray://explicit:10001")
    assert inits[-1] == {"address": "ray://explicit:10001"}

    monkeypatch.delenv("RAY_ADDRESS")
    monkeypatch.delenv("RLT_RAY_ADDRESS")
    RayBackend()
    assert inits[-1] == {}
    sys.modules.pop("ray_lightning_tpu.cluster.ray_backend", None)
    sys.modules.pop("ray_lightning_tpu.cluster.queue", None)


def test_rlt_backend_env_selection(monkeypatch):
    """RLT_BACKEND=local forces the builtin backend even with Ray
    importable; RLT_BACKEND=ray errors clearly when Ray is absent."""
    from ray_lightning_tpu.cluster import backend as backend_mod
    from ray_lightning_tpu.cluster.local import LocalBackend

    _install_stub_ray(monkeypatch)
    monkeypatch.setattr(
        "ray_lightning_tpu.utils.imports.RAY_AVAILABLE", True)

    backend_mod.set_backend(None)
    monkeypatch.setenv("RLT_BACKEND", "local")
    try:
        assert isinstance(backend_mod.get_backend(), LocalBackend)

        backend_mod.set_backend(None)
        monkeypatch.setenv("RLT_BACKEND", "ray")
        monkeypatch.setattr(
            "ray_lightning_tpu.utils.imports.RAY_AVAILABLE", False)
        with pytest.raises(ImportError, match="RLT_BACKEND=ray"):
            backend_mod.get_backend()

        backend_mod.set_backend(None)
        monkeypatch.setenv("RLT_BACKEND", "bogus")
        with pytest.raises(ValueError, match="bogus"):
            backend_mod.get_backend()
    finally:
        backend_mod.set_backend(None)
        sys.modules.pop("ray_lightning_tpu.cluster.ray_backend", None)
        sys.modules.pop("ray_lightning_tpu.cluster.queue", None)


def test_queue_lazy_and_zero_cpu(ray_backend):
    backend, _ = ray_backend
    assert backend.queue_get_nowait() is None  # no queue yet
    backend.worker_queue_proxy()
    q = backend._queue
    assert q.actor_options == {"num_cpus": 0}  # ray_ddp.py:338 parity
    q.items.append("x")
    assert backend.queue_get_nowait() == "x"
    assert backend.queue_get_nowait() is None
    backend.shutdown()
    assert q.shut and backend._queue is None
