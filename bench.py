"""Benchmark: GPT-2-small training steps/sec through the full framework
path (Trainer → compiled SPMD train step) on whatever accelerator is
attached (one TPU chip under the driver; CPU elsewhere).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "steps/sec", "vs_baseline": N}

The reference publishes no numbers (BASELINE.md); ``vs_baseline`` is
measured against the stored first-round value below so rounds are
comparable to each other.
"""

from __future__ import annotations

import json
import sys
import time

# First recorded values per (platform, config) so vs_baseline always
# compares like with like.  TPU: one v5e chip, gpt2-small (seq 1024,
# bf16 compute, remat off — remat recompute cost ~20% steps/sec), batch
# 8 — round-1 measurement of this exact config.  The earlier 27.0 was a
# stale seq-512 figure; a raw-jax loop of the identical seq-1024 step
# measures the same 10 steps/sec as the framework path (zero overhead).
# CPU: tiny config, smoke-run hardware.
BASELINES = {
    "gpt2s_train_steps_per_sec_tpu": 10.0,
    "gpt2tiny_train_steps_per_sec_cpu": 25.0,
}

WARMUP_STEPS = 3
TIMED_STEPS = 30


def main() -> None:
    import jax
    import numpy as np

    from ray_lightning_tpu import Trainer
    from ray_lightning_tpu.core.callbacks import Callback
    from ray_lightning_tpu.models.gpt import CONFIGS, GPTLightningModule

    platform = jax.devices()[0].platform
    if platform == "cpu":
        # keep CPU smoke runs tractable; the driver benches on TPU
        cfg, batch = CONFIGS["tiny"], 8
        metric = "gpt2tiny_train_steps_per_sec_cpu"
    else:
        cfg, batch = CONFIGS["gpt2-small"], 8
        metric = f"gpt2s_train_steps_per_sec_{platform}"

    module = GPTLightningModule(
        cfg, dataset_size=batch * (WARMUP_STEPS + TIMED_STEPS),
        batch_size=batch)

    class Timer(Callback):
        def __init__(self):
            self.t0 = None
            self.elapsed = None

        def on_train_batch_end(self, trainer, mod, metrics, batch, idx):
            # device→host fetch of the loss scalar is the sync point
            # (block_until_ready does not reliably drain remote-tunnel
            # platforms, so fetch a value instead)
            if trainer.global_step == WARMUP_STEPS:
                float(np.asarray(metrics["loss"]))
                self.t0 = time.monotonic()
            elif trainer.global_step == WARMUP_STEPS + TIMED_STEPS:
                float(np.asarray(metrics["loss"]))
                self.elapsed = time.monotonic() - self.t0

    timer = Timer()
    trainer = Trainer(
        max_steps=WARMUP_STEPS + TIMED_STEPS, max_epochs=1,
        enable_checkpointing=False, num_sanity_val_steps=0,
        limit_val_batches=0, log_every_n_steps=10**9,
        callbacks=[timer], seed=0)
    trainer.fit(module)

    assert timer.elapsed is not None, "benchmark did not reach timed steps"
    steps_per_sec = TIMED_STEPS / timer.elapsed
    baseline = BASELINES.get(metric, steps_per_sec)
    print(json.dumps({
        "metric": metric,
        "value": round(steps_per_sec, 3),
        "unit": "steps/sec",
        "vs_baseline": round(steps_per_sec / baseline, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
