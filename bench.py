"""Benchmark: GPT-2-small training steps/sec through the full framework
path (Trainer → compiled SPMD train step) on whatever accelerator is
attached (one TPU chip under the driver; CPU elsewhere).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "steps/sec", "vs_baseline": N,
   "device_ms": M, "telemetry_jsonl": "<path>",
   "hbm_peak_bytes": N, "collective_gibs": N,
   "time_to_first_step_seconds": N, "compile_cache": "hit|miss|off"}

``telemetry_jsonl`` points at the run's exported span/counter stream
(telemetry/): BENCH rounds can attribute a regression to a phase
(step vs data_wait vs compile) straight from the recorded spans.
``hbm_peak_bytes`` / ``collective_gibs`` come from the metrics plane
(telemetry/metrics.py) so rounds track memory and comms regressions
alongside steps/sec.  ``time_to_first_step_seconds`` and
``compile_cache`` come from the compile plane (compile/): set
``RLT_COMPILE_CACHE=1`` and run twice to measure the cold→warm startup
win the persistent compilation cache buys.

``value`` is wall steps/sec (the BASELINE.md bar as specified);
``device_ms`` is the median device time of the compiled train step
from a warm-tail trace — the tunnel-immune number: wall swings ±3-5%
with host-link state (VERDICT r3 weak #1), device time repeats to <1%.

The reference publishes no numbers (BASELINE.md); ``vs_baseline`` is
measured against the stored first-round value below so rounds are
comparable to each other.  Timing/emission logic lives in
``benchmarks/harness.py``, shared with the per-config scripts under
``benchmarks/``.

The line also carries ``anatomy`` — the measured per-step device-time
split (compute/collective/exposed/host, telemetry/anatomy.py) parsed
from the same warm-tail trace as ``device_ms``.  ``--compare
prev.json`` (a BENCH_r*.json blob or a file of bench JSON lines) runs
the perf-regression ledger (benchmarks/ledger.py) over this round's
records and exits nonzero when step time, device_ms or exposed-comm
regresses past its band — the pre-merge perf gate.
"""

from __future__ import annotations

import json
import os
import sys

# First recorded values per (platform, config) so vs_baseline always
# compares like with like.  TPU: one v5e chip, gpt2-small (seq 1024,
# bf16 compute, remat off — remat recompute cost ~20% steps/sec), batch
# 8 — round-1 measurement of this exact config.  The earlier 27.0 was a
# stale seq-512 figure; a raw-jax loop of the identical seq-1024 step
# measures the same 10 steps/sec as the framework path (zero overhead).
# CPU: tiny config, smoke-run hardware.
BASELINES = {
    "gpt2s_train_steps_per_sec_tpu": 10.0,
    "gpt2tiny_train_steps_per_sec_cpu": 25.0,
}

WARMUP_STEPS = 3
TIMED_STEPS = 30


def main(argv=None) -> int:
    import argparse

    import jax

    from benchmarks.harness import run_steps_per_sec
    from ray_lightning_tpu.models.gpt import CONFIGS, GPTLightningModule

    parser = argparse.ArgumentParser(
        description="Headline bench; --compare turns it into the "
        "pre-merge perf-regression gate (benchmarks/ledger.py).")
    parser.add_argument(
        "--compare", metavar="PREV_JSON", default=None,
        help="previous round (a BENCH_r*.json blob or a file of bench "
        "JSON lines); after the run the ledger compares this round's "
        "records against it and the process exits nonzero when step "
        "time, device_ms or exposed-comm regresses past its band")
    parser.add_argument(
        "--out", metavar="CURR_JSON", default=None,
        help="also write this round's records as JSON lines (the file "
        "a later --compare can read)")
    args = parser.parse_args(argv)

    platform = jax.devices()[0].platform
    if platform == "cpu":
        # keep CPU smoke runs tractable; the driver benches on TPU
        cfg, batch = CONFIGS["tiny"], 8
        metric = "gpt2tiny_train_steps_per_sec_cpu"
    else:
        cfg, batch = CONFIGS["gpt2-small"], 8
        metric = f"gpt2s_train_steps_per_sec_{platform}"

    trace_steps = 8
    module = GPTLightningModule(
        cfg, dataset_size=batch * (WARMUP_STEPS + TIMED_STEPS + trace_steps),
        batch_size=batch)
    results = [run_steps_per_sec(
        module, metric, warmup=WARMUP_STEPS,
        timed=TIMED_STEPS, baseline=BASELINES.get(metric),
        trace_steps=trace_steps, inline_device_ms=True)]

    if os.environ.get("RLT_REMAT_AB") == "1":
        # remat-policy ladder (benchmarks/bench_remat.py): compile +
        # time every feasible policy of the headline fixture's
        # configure_remat() ladder and emit ONE `remat` JSON field —
        # per-policy device ms/step + HBM peak + measured winner vs the
        # hand-picked default (gap documented when the hand pick wins).
        from benchmarks.bench_remat import run_remat_ab
        run_remat_ab(metric + "_remat")

    if os.environ.get("RLT_COMM_AB") == "1":
        # comm-plane A/B legs (benchmarks/bench_comm.py): fp32 floor,
        # flat int8, hierarchical int8/fp8/int4, and the bucketed-vs-
        # barrier overlap pair — one JSON line per leg with
        # ``exposed_comm_seconds`` (wall minus the fp32 floor) so the
        # tentpole's overlap win is a single diff.  Runs inline on a
        # multi-device mesh; a single-device session re-runs the legs
        # on the 8-virtual-device CPU proxy in a subprocess.
        from benchmarks.bench_comm import run_comm_ab
        comm_results = run_comm_ab(metric + "_comm")
        if comm_results:
            results.extend(comm_results)

    if os.environ.get("RLT_FLEET_AB") == "1":
        # fleet-plane traffic replay (benchmarks/bench_fleet.py): record
        # a multi-tenant trace, replay at 1x/2x/4x against 1 vs 2
        # replicas plus an autoscaling 1→3 leg — one `fleet` JSON line
        # with tokens/s + TTFT per multiplier, autoscale events, the
        # prefix-reuse ratio and the greedy-parity verdict.  Joins the
        # --compare ledger via fleet.tokens_per_sec / fleet.ttft_p99_ms.
        from benchmarks.bench_fleet import run_fleet_ab
        fleet_results = run_fleet_ab(metric + "_fleet")
        if fleet_results:
            results.extend(fleet_results)

    if args.out:
        with open(args.out, "w") as f:
            for r in results:
                f.write(json.dumps(r) + "\n")

    if args.compare:
        # perf-regression ledger (benchmarks/ledger.py): this round's
        # records vs the given previous round — the pre-merge gate.
        # Nonzero exit when step time / device_ms / exposed-comm
        # regresses past its band.
        from benchmarks import ledger
        report = ledger.compare(args.compare, results)
        print(json.dumps(report))
        return 0 if report["ok"] else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
