#!/usr/bin/env bash
# Lint/format gate (reference: format.sh — yapf+flake8, diff-vs-merge-base
# or --all).  This build standardizes on flake8 only; CI runs the same
# invocation (.github/workflows/test.yaml lint job).
#
# Usage:
#   ./format.sh                  # lint files changed vs the merge-base with main
#   ./format.sh --all            # lint the whole tree
#   ./format.sh --check [--all]  # explicit non-mutating check mode for CI:
#                                # guaranteed to touch no files, exits nonzero
#                                # on findings (same lint; the flag exists so
#                                # CI stays correct if a mutating formatter is
#                                # ever added to the default path)

set -euo pipefail
cd "$(dirname "$0")"

FLAKE8_ARGS=(--max-line-length=88 --extend-ignore=E203,W503)

CHECK=0
ALL=0
for arg in "$@"; do
    case "$arg" in
        --check) CHECK=1 ;;
        --all)   ALL=1 ;;
        *) echo "usage: $0 [--check] [--all]" >&2; exit 2 ;;
    esac
done
# --check is non-mutating by construction: only checks run below.
if [[ "$CHECK" == 1 ]]; then
    # metrics-name lint: every instrument registered anywhere in the
    # package must be Prometheus-clean — rlt_ prefix + a unit suffix
    # (_bytes/_seconds/_total) — so the driver's /metrics exposition
    # never emits an unscrapable series (telemetry/metrics.py).
    # (-c entry, not -m: the telemetry package imports the module at
    # init, and runpy would re-execute it with a RuntimeWarning)
    python -c 'import sys; from ray_lightning_tpu.telemetry.metrics \
        import _main; sys.exit(_main(["--check-names"]))'
    # compile-plane selfcheck: env knobs round-trip through worker_env,
    # the cache-seeding pack/unpack round-trips, and every metric the
    # compile plane publishes is covered by the name lint above
    # (ray_lightning_tpu/compile/selfcheck.py; no jax backend touched)
    python -c 'import sys; from ray_lightning_tpu.compile.selfcheck \
        import _main; sys.exit(_main([]))'
    # comm-plane selfcheck: the compression policy resolves correctly on
    # every built-in strategy, RLT_COMM* env knobs round-trip, and the
    # compressed collectives lower without error on a small virtual CPU
    # mesh (ray_lightning_tpu/comm/selfcheck.py)
    python -c 'import sys; from ray_lightning_tpu.comm.selfcheck \
        import _main; sys.exit(_main([]))'
    # ops-plane selfcheck: decode-impl resolution precedence, the
    # flash-decode grid-skip invariant (the index-map clamp and the
    # kernel's compute guard must agree on every block), geometry
    # gating, interpreter lowering parity vs the dense einsum, and the
    # identity-page-table round-trip (ray_lightning_tpu/ops/selfcheck.py)
    python -c 'import sys; from ray_lightning_tpu.ops.selfcheck \
        import _main; sys.exit(_main([]))'
    # serve-plane selfcheck: bucket resolution + padding, scheduler
    # invariants (slot uniqueness, tenant quota, fair-share progress)
    # under a simulated multi-tenant run, serve metric names, and the
    # prefill/decode programs lowering on a CPU mesh
    # (ray_lightning_tpu/serve/selfcheck.py)
    python -c 'import sys; from ray_lightning_tpu.serve.selfcheck \
        import _main; sys.exit(_main([]))'
    # fleet-plane selfcheck: FleetConfig/PageConfig validation +
    # RLT_FLEET*/RLT_SERVE_PAGED* env round-trip, page free-list
    # accounting, prefix-hash round-trip (collision-verified), the
    # autoscaler patience/cooldown state machine, router least-loaded/
    # sticky/affinity/quota invariants, the federation directory
    # (register/lookup/invalidate round-trip, liveness expiry,
    # collision-proof routing, retained-page size bound),
    # rlt_fleet_* metric names
    # (ray_lightning_tpu/serve/fleet/selfcheck.py)
    python -c 'import sys; from ray_lightning_tpu.serve.fleet.selfcheck \
        import _main; sys.exit(_main([]))'
    # elastic-plane selfcheck: ElasticConfig validation + RLT_ELASTIC*
    # env round-trip, fault-spec parsing, elastic metric names, and the
    # residual re-bucket's injected-error invariant on a CPU array
    # (ray_lightning_tpu/elastic/selfcheck.py)
    python -c 'import sys; from ray_lightning_tpu.elastic.selfcheck \
        import _main; sys.exit(_main([]))'
    # planner-plane selfcheck: PlanConfig validation + RLT_PLAN* env
    # round-trip, enumeration coverage/pruning reasons, byte→seconds
    # score monotonicity, PlanReport schema, plan metric names
    # (ray_lightning_tpu/plan/selfcheck.py)
    python -c 'import sys; from ray_lightning_tpu.plan.selfcheck \
        import _main; sys.exit(_main([]))'
    # mpmd-plane selfcheck: schedule invariants (every microbatch F
    # before its B, 1F1B depth <= stages x virtual, the plain-1F1B
    # bubble tie + interleaved win), RLT_MPMD* env round-trip, channel
    # codec round-trip / out-of-order / dead-peer timeout, stage-cut
    # resolution, metric names (ray_lightning_tpu/mpmd/selfcheck.py)
    python -c 'import sys; from ray_lightning_tpu.mpmd.selfcheck \
        import _main; sys.exit(_main([]))'
    # trace-plane selfcheck: span-record schema, trace-context
    # round-trip (driver + worker spans reassemble one request tree),
    # flight-recorder bounded-size invariant, profile-controller state
    # machine, trace-plane + anatomy metric names, the anatomy parser
    # on the golden synthetic fixture (exposed-comm overlap math + the
    # wall = compute + exposed + host identity), and the
    # TelemetryConfig anatomy knobs round-tripping through
    # worker_env/RLT_ANATOMY* (ray_lightning_tpu/telemetry/selfcheck.py)
    python -c 'import sys; from ray_lightning_tpu.telemetry.selfcheck \
        import _main; sys.exit(_main([]))'
fi

if [[ "$ALL" == 1 ]]; then
    exec flake8 "${FLAKE8_ARGS[@]}" ray_lightning_tpu tests benchmarks bench.py __graft_entry__.py
fi

MERGEBASE="$(git merge-base origin/main HEAD 2>/dev/null \
             || git merge-base main HEAD 2>/dev/null \
             || git rev-parse HEAD~1)"
FILES="$(git diff --name-only --diff-filter=ACRM "$MERGEBASE" -- '*.py')"
if [[ -z "$FILES" ]]; then
    echo "No changed python files."
    exit 0
fi
# shellcheck disable=SC2086
exec flake8 "${FLAKE8_ARGS[@]}" $FILES
